"""JG030 — quantized-variant precision/cast mismatch.

The quant plane's contract (docs/QUANT.md): a bundle manifest's
``precision`` field is LOAD-BEARING. The serving engine reads it and
compiles the variant's AOT executables accordingly — ``"bf16"`` traces
under a bfloat16 compute scope, ``"int8"`` expects QuantDenseLayer
int8 weights. A builder that *declares* one precision while *casting*
its params to a different low-precision dtype ships a bundle whose
numerics and cost block silently disagree with what the mux plane and
the canary believe they adopted: a ``precision: "bf16"`` manifest over
``astype(jnp.float16)`` params serves fp16 rounding under a bf16
compute scope (two incompatible 16-bit formats — different exponent
widths), and the measured cost ledger prices the wrong artifact.

The rule is scope-local per function: collect every *declared* variant
precision — a ``"precision"`` key in a dict literal, a
``manifest["precision"] = ...`` subscript store, or a ``precision=``
call kwarg, with a constant-string value of ``"bf16"`` or ``"int8"`` —
and every *low-precision cast* in the same scope (``.astype(d)`` or a
``dtype=d`` kwarg where ``d`` resolves to a sub-f32 dtype:
``jnp.bfloat16``/``float16``/``int8``/``uint8``, numpy spellings
included). When a scope declares exactly one quantized precision and
casts to low-precision dtypes but NONE of them match the declaration,
the declaration is flagged.

True negatives: a scope whose casts include the declared dtype (extra
f32 upcasts alongside are fine — dequant outputs are float by design);
declarations with no low-precision cast at all (the builder may copy
checkpoints byte-identical, as the int8 generator path does);
non-constant or non-quantized precision values; scopes declaring both
precisions (a dispatch table, not a builder). Known false negatives:
builder halves split across functions (declare here, cast in a helper)
— the cast evidence is scope-local by design, an unresolved helper must
not indict correct code.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

#: manifest precision strings the quant plane defines (docs/QUANT.md)
_QUANT_PRECISIONS = ("bf16", "int8")

#: resolved dtype dotted-name → the manifest precision it implements
_DTYPE_PRECISION = {
    "jax.numpy.bfloat16": "bf16",
    "numpy.bfloat16": "bf16",
    "ml_dtypes.bfloat16": "bf16",
    "jax.numpy.int8": "int8",
    "numpy.int8": "int8",
    # sub-f32 dtypes that implement NO declared precision — evidence of
    # a cast mismatch when one is declared
    "jax.numpy.float16": "fp16",
    "numpy.float16": "fp16",
    "jax.numpy.uint8": "uint8",
    "numpy.uint8": "uint8",
    "jax.numpy.int4": "int4",
}


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _declared_precisions(scope) -> List[Tuple[str, ast.AST]]:
    """(precision, node) per declaration site inside the scope."""
    out = []
    for n in ast.walk(scope):
        if isinstance(n, ast.Dict):
            for k, v in zip(n.keys, n.values):
                if _const_str(k) == "precision":
                    p = _const_str(v)
                    if p in _QUANT_PRECISIONS:
                        out.append((p, v))
        elif isinstance(n, ast.Assign):
            for t in n.targets:
                if (isinstance(t, ast.Subscript)
                        and _const_str(t.slice) == "precision"):
                    p = _const_str(n.value)
                    if p in _QUANT_PRECISIONS:
                        out.append((p, n.value))
        elif isinstance(n, ast.Call):
            for kw in n.keywords:
                if kw.arg == "precision":
                    p = _const_str(kw.value)
                    if p in _QUANT_PRECISIONS:
                        out.append((p, kw.value))
    return out


def _cast_precisions(scope, resolve) -> Dict[str, ast.AST]:
    """precision-tag → first cast node, for every low-precision cast:
    ``x.astype(dtype)`` and ``dtype=`` kwargs, resolved through the
    module's import aliases."""
    found: Dict[str, ast.AST] = {}

    def _note(expr):
        tag = _DTYPE_PRECISION.get(resolve(expr) or "")
        if tag is not None and tag not in found:
            found[tag] = expr

    for n in ast.walk(scope):
        if not isinstance(n, ast.Call):
            continue
        if (isinstance(n.func, ast.Attribute) and n.func.attr == "astype"
                and n.args):
            _note(n.args[0])
        for kw in n.keywords:
            if kw.arg == "dtype":
                _note(kw.value)
    return found


class QuantPrecisionCastMismatch:
    code = "JG030"
    name = "quant-precision-cast-mismatch"
    summary = ("manifest declares one quantized precision but the params "
               "are cast to a different low-precision dtype — the engine "
               "compiles for the declaration, not the bytes")

    def check(self, mod):
        for scope in ast.walk(mod.tree):
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            declared = _declared_precisions(scope)
            precisions = {p for p, _ in declared}
            if len(precisions) != 1:
                # no declaration, or a bf16+int8 dispatch table — not a
                # single-variant builder, nothing to contradict
                continue
            precision = next(iter(precisions))
            casts = _cast_precisions(scope, mod.resolve)
            if not casts or precision in casts:
                continue
            others = ", ".join(sorted(casts))
            for p, node in declared:
                f = mod.finding(
                    self.code,
                    f"declares variant precision \"{p}\" but this scope "
                    f"casts params to {others} and never to {p} — the "
                    f"serving engine compiles its executables for the "
                    f"DECLARED precision (bf16 compute scope / int8 "
                    f"QuantDenseLayer weights), so the shipped bytes and "
                    f"the compiled numerics disagree; cast with the "
                    f"matching dtype or fix the manifest field",
                    node,
                )
                yield f, scope
