"""JG021 — subprocess respawn loop with no attempt cap and no backoff.

The fleet manager relaunches dead workers from a supervision loop; the
hazard this rule polices arrived with it. A worker that dies *before
ever becoming routable* (a bundle that segfaults every boot, a poisoned
environment) turns an eager ``while alive: relaunch()`` supervisor into
a hot loop: a fresh process per scheduler tick, each one paying the full
interpreter + jax import cost, saturating the host the surviving
workers are trying to serve from — a fork bomb with extra steps. The
corrected idiom is the manager's spawn-failure backoff: count failures,
relaunch on a capped exponential schedule, surface a counter.

The rule: a ``while`` loop whose body reaches a process-spawning entry
point (:data:`_common.SPAWN_CALLS` — directly, or transitively through
a project function per the index's spawn-taint closure, constructors
included) is flagged when the loop has NEITHER

- an **attempt cap** — a comparison in the loop condition
  (``while relaunches <= budget:``, ``while candidate is None:`` —
  progress-shaped conditions that bound the loop), NOR
- a **backoff sleep** — ``time.sleep(...)``, a ``.sleep(...)`` method
  call, or a ``.wait(<timeout>)`` method call WITH an argument
  (``Event.wait(0.2)`` is the supervision loop's idiomatic pacer).
  An *argless* ``.wait()`` is NOT a pacer: ``while True:
  p = Popen(cmd); p.wait()`` is the canonical naive supervisor, and
  ``Popen.wait`` returns instantly when the child dies at boot — the
  loop forks as fast as the host allows.

``for`` loops are iteration-bounded by construction and never flagged.
Test modules are exempt (``skip_tests`` — test harnesses relaunch under
their own timeouts).
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common

#: method names that pace a loop (event.wait, stop.wait, time-ish sleeps
#: reached as attributes)
_PACER_METHODS = {"sleep", "wait"}


class UnboundedRespawnLoop:
    code = "JG021"
    name = "unbounded-respawn-loop"
    summary = ("subprocess spawn inside a while loop with neither an "
               "attempt cap nor a backoff sleep — a process that dies "
               "on every boot relaunches as fast as the host can fork")
    skip_tests = True

    def check(self, mod):
        for loop in ast.walk(mod.tree):
            if not isinstance(loop, ast.While):
                continue
            if self._capped(loop.test):
                continue
            body = list(_common.walk_excluding_defs(loop.body))
            if self._paced(body, mod):
                continue
            for call in body:
                if not isinstance(call, ast.Call):
                    continue
                spawner = self._spawn_target(call, mod)
                if spawner is None:
                    continue
                yield mod.finding(
                    self.code,
                    f"`{spawner}` is reached from an unbounded `while` "
                    f"loop with no backoff sleep on the respawn path — "
                    f"a process that dies before becoming healthy "
                    f"relaunches in a hot loop (one fresh process per "
                    f"iteration); cap the attempts or back off with a "
                    f"capped exponential sleep",
                    call,
                ), call

    @staticmethod
    def _capped(test: ast.expr) -> bool:
        """A comparison anywhere in the loop condition is read as an
        attempt cap / progress bound (``attempts < budget``,
        ``proc.poll() is None``). ``while True`` and event-flag shapes
        (``while not stop.is_set():``) are the unbounded supervisors
        this rule exists for."""
        return any(isinstance(n, ast.Compare) for n in ast.walk(test))

    @staticmethod
    def _paced(body, mod) -> bool:
        for n in body:
            if not isinstance(n, ast.Call):
                continue
            if mod.resolve(n.func) in _common.SLEEP_CALLS:
                return True
            if not (isinstance(n.func, ast.Attribute)
                    and n.func.attr in _PACER_METHODS):
                continue
            if n.func.attr == "sleep":
                return True
            # `.wait(...)` paces only WITH an argument: `stop.wait(0.2)`
            # bounds the iteration, while an argless `p.wait()` is the
            # naive supervisor blocking on a child that may die at boot
            # — Popen.wait returns instantly then, and the loop is hot
            if n.args or n.keywords:
                return True
        return False

    @staticmethod
    def _spawn_target(call: ast.Call, mod):
        """The spawning callee this call reaches, or None: a direct
        :data:`_common.SPAWN_CALLS` hit, or a project function whose
        spawn-taint closure is true (class constructors resolved through
        their ``__init__``)."""
        resolved = mod.resolve(call.func)
        if resolved in _common.SPAWN_CALLS:
            return resolved
        if mod.project is None:
            return None
        summary = mod.project.resolve_function(mod, call.func)
        if summary is None:
            # constructor shape: WorkerProcess(...) summarizes as
            # WorkerProcess.__init__ in the index (imported classes
            # resolve through the import map; module-local ones straight
            # off this module's function table)
            dotted = _common.dotted_name(call.func)
            if dotted is not None:
                summary = mod.project.resolve_function(
                    mod, f"{dotted}.__init__")
                if summary is None:
                    info = mod.project.by_path.get(mod.path)
                    if info is not None:
                        summary = info.functions.get(f"{dotted}.__init__")
        if summary is not None and mod.project.spawn_tainted(summary):
            return summary.fq
        return None
