"""JG003 — bare ``assert`` enforcing runtime invariants in non-test code.

``python -O`` strips every assert. A protocol guard written as an assert —
like the pre-round-6 ``bench.py`` line-length check protecting the driver's
2,000-char stdout tail window — simply vanishes in optimized deployments,
and the failure it guarded (an unparseable oversize line voiding a whole
bench round) comes back silently. Runtime invariants in production code must
be explicit ``if ...: raise``/handle blocks.

Tests are exempt (``skip_tests``): pytest rewrites asserts, they are the
assertion mechanism there. ``assert False`` variants used as unreachable
markers are still flagged — ``raise AssertionError`` spells that intent
survivably.
"""

from __future__ import annotations

import ast


class BareAssert:
    code = "JG003"
    name = "bare-assert"
    summary = "assert enforces a runtime invariant — stripped under python -O"
    skip_tests = True

    def check(self, mod):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assert):
                f = mod.finding(
                    self.code,
                    "bare assert is stripped under `python -O` — enforce "
                    "this invariant with an explicit check that raises or "
                    "handles the violation",
                    node,
                )
                yield f, node
