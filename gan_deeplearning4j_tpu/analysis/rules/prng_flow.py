"""JG014 — cross-module PRNG key reuse.

JG001 sees a key consumed twice by *jax.random* calls in one scope. It is
blind to the indirection this repo actually uses: a key handed to a helper
function (``sample_z(key, n)``) that consumes it internally. Passing the
same key to two such helpers — or to a helper AND a direct ``jax.random``
draw — correlates their streams exactly like the scope-local bug, and no
scope-local rule can see it because the consumption happens a module away.

This rule consumes the project index's ``prng_params`` summaries (recorded
since PR 2, unconsumed until now — the ROADMAP item). A *hand-off* is a
call whose callee resolves to an indexed project function and whose
argument lands on a parameter the summary marks PRNG-like; it only counts
when the callee (transitively, over resolved project calls) actually
consumes entropy — a derive-only helper (``wkey = lambda k: fold_in(k, i)``
style) is not a consumer, so handing the same base key to it twice with
different salts stays silent.

Findings fire on the same-scope straight-line pattern (two uses of one key
expression with no rebinding between) and on the loop-replay pattern (a
consuming hand-off inside a loop whose key derives from nothing bound per
iteration). Pairs where BOTH uses are direct ``jax.random`` calls are
JG001's findings, not ours — one defect, one code.

Known false-negative classes (deliberate, silent side): keys smuggled
through containers or object attributes; callees resolvable only through
``self.``-dispatch; entropy consumption behind an unresolvable call.

``skip_tests``: test modules reuse keys *deliberately* (same-key parity
and determinism assertions are the point of half of ``tests/test_rng.py``),
so the cross-module rule exempts them like JG003 does.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional

from gan_deeplearning4j_tpu.analysis import _common
from gan_deeplearning4j_tpu.analysis.rules.prng import (
    _consumer_name,
    _expr_base,
    _key_arg,
    _stmt_eval_roots,
)


class CrossModulePrngReuse:
    code = "JG014"
    name = "prng-key-reuse-cross-module"
    summary = ("same PRNG key handed to two entropy-consuming calls "
               "(project key-taking functions included) without an "
               "intervening split/fold_in")
    skip_tests = True

    def check(self, mod):
        index = getattr(mod, "project", None)
        if index is None:  # single-module entry without phase 1 — no facts
            return
        self._consumes_cache: Dict[str, bool] = {}
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None)
            if not body:
                continue
            yield from self._scan_block(body, {}, mod, index)

    # -- entropy consumption (transitive, over the index) -----------------
    def _consumes_entropy(self, summary, index, seen=frozenset()) -> bool:
        """Does ``summary`` draw from jax.random (directly or through
        resolved project calls)? Unresolvable callees count as 'no' — the
        silent side; a derive-only helper must not turn its callers'
        salted hand-offs into findings."""
        if summary.fq in self._consumes_cache:
            return self._consumes_cache[summary.fq]
        if summary.fq in seen:
            return False
        owner = index.modules.get(summary.module)
        if owner is None or summary.node is None:
            return False
        for node in ast.walk(summary.node):
            if isinstance(node, ast.Call) and _consumer_name(
                    node, owner.srcmod) is not None:
                self._consumes_cache[summary.fq] = True
                return True
        seen = seen | {summary.fq}
        for callee in summary.calls:
            target = index.lookup(callee)
            if target is not None and self._consumes_entropy(
                    target, index, seen):
                self._consumes_cache[summary.fq] = True
                return True
        self._consumes_cache[summary.fq] = False
        return False

    # -- per-statement uses ----------------------------------------------
    def _uses_in(self, roots, mod, index):
        """(call, key_expr_node, description, is_handoff) for every
        entropy use under ``roots``: direct jax.random consumers plus
        hand-offs into consuming project functions."""
        out = []
        for node in _common.walk_excluding_defs(roots):
            if not isinstance(node, ast.Call):
                continue
            fn = _consumer_name(node, mod)
            if fn is not None:
                key = _key_arg(node)
                if key is not None:
                    out.append((node, key, f"jax.random.{fn}", False))
                continue
            summary = index.resolve_function(mod, node.func)
            if summary is None or not summary.prng_params:
                continue
            if not self._consumes_entropy(summary, index):
                continue
            for i, arg in enumerate(node.args):
                if (i < len(summary.params)
                        and summary.params[i] in summary.prng_params):
                    out.append((node, arg, summary.fq, True))
            for kw in node.keywords:
                if kw.arg in summary.prng_params and kw.value is not None:
                    out.append((node, kw.value, summary.fq, True))
        out.sort(key=lambda t: (t[0].lineno, t[0].col_offset))
        return out

    def _stmt_uses(self, stmt, mod, index):
        return self._uses_in(_stmt_eval_roots(stmt), mod, index)

    # -- block scan (JG001's shape, mixed-use tracking) -------------------
    def _scan_block(self, stmts, used, mod, index):
        """``used``: key expression text -> (line, description,
        is_handoff). A second use fires only when at least one side is a
        hand-off — direct/direct pairs are JG001's findings."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes handled by iter_scopes
            for call, key, desc, is_handoff in self._stmt_uses(
                    stmt, mod, index):
                expr = ast.unparse(key)
                if expr in used:
                    first_line, first_desc, first_handoff = used[expr]
                    if is_handoff or first_handoff:
                        f = mod.finding(
                            self.code,
                            f"PRNG key `{expr}` already consumed by "
                            f"{first_desc} at line {first_line} — this "
                            f"call consumes the same stream "
                            f"({desc} takes it as a PRNG key); "
                            f"split/fold_in between the two",
                            call,
                        )
                        yield f, call
                else:
                    used[expr] = (call.lineno, desc, is_handoff)
            rebound = _common.assignment_targets(stmt)
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                _common._target_names(stmt.target, rebound)
            if rebound:
                for expr in [e for e in used if _expr_base(e) in rebound]:
                    del used[expr]
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                yield from self._scan_loop(stmt, dict(used), mod, index)
            elif isinstance(stmt, ast.If):
                yield from self._scan_block(stmt.body, dict(used), mod, index)
                yield from self._scan_block(stmt.orelse, dict(used), mod,
                                            index)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._scan_block(stmt.body, used, mod, index)
            elif isinstance(stmt, ast.Try):
                for block in (stmt.body, stmt.orelse, stmt.finalbody):
                    yield from self._scan_block(block, used, mod, index)
                for handler in stmt.handlers:
                    yield from self._scan_block(handler.body, dict(used),
                                                mod, index)

    def _scan_loop(self, loop, used, mod, index):
        """Hand-off loop replay: a consuming hand-off whose key derives
        from nothing the loop binds replays one stream every iteration
        (JG001 owns the direct-consumer version of this check)."""
        yield from self._scan_block(loop.body, used, mod, index)
        loop_bound = _common.bound_names(loop)
        for call, key, desc, is_handoff in self._uses_in(
                loop.body, mod, index):
            if not is_handoff:
                continue
            if not (_common.loaded_names(key) & loop_bound):
                expr = ast.unparse(key)
                f = mod.finding(
                    self.code,
                    f"PRNG key `{expr}` handed to {desc} inside a loop "
                    f"but derived outside it — every iteration replays "
                    f"the same stream; fold_in the loop index",
                    call,
                )
                yield f, call
