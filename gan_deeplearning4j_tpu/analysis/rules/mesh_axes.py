"""JG013 — sharding spec names an axis the mesh does not have.

A ``PartitionSpec`` is only meaningful relative to a mesh: every axis name
it mentions must be an axis of the mesh it is paired with (via
``NamedSharding(mesh, spec)`` or ``shard_map(..., mesh=mesh,
in_specs=..., out_specs=...)``). Get a name wrong — a renamed mesh axis,
a spec copy-pasted from a 2-D-mesh trainer into a 1-D-mesh consumer — and
jax raises only when the sharding is first USED, which on this repo's
target platform is minutes into a run, after the XLA compile queue, on an
exclusively-held chip. The serving engine's replica mesh
(``serving/engine.py``: a 1-D ``("replica",)`` mesh whose bulk lane
shards batches with ``PartitionSpec("replica")``) is the in-tree consumer
this rule watches; the training meshes (``("data",)``, harness +
parallel/) are the other.

The rule fires only on statically-certain evidence: the mesh variable
must be bound exactly once in the same scope to a ``Mesh``/``make_mesh``
construction with a literal axis-name tuple, and the spec must be a
``PartitionSpec(...)`` call with literal string axes. It flags

1. an axis name that is not an axis of the mesh, and
2. one mesh axis used for two different dimensions of one spec (invalid:
   an axis can shard at most one dimension).

``None`` entries, unresolvable meshes, and non-literal specs are silence,
not a guess.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from gan_deeplearning4j_tpu.analysis import _common

_MESH_CTORS = {
    "jax.sharding.Mesh", "jax.interpreters.pxla.Mesh", "jax.make_mesh",
    "jax.experimental.mesh_utils.Mesh", "jax.sharding.make_mesh",
}
_NAMED_SHARDING = {"jax.sharding.NamedSharding"}
_SHARD_MAP = {"jax.shard_map", "jax.experimental.shard_map.shard_map"}
_PSPEC = {"jax.sharding.PartitionSpec"}


def _axis_names(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """Literal mesh axis names: a tuple/list of str constants, or a lone
    str constant (a 1-D mesh may be declared either way)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        names = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            names.append(elt.value)
        return tuple(names)
    return None


def _scope_walk(scope):
    """Walk the scope's OWN statements. ``walk_excluding_defs`` skips defs
    it meets as children but descends into defs handed to it as roots —
    and a module's body contains its functions as root statements — so
    nested defs are filtered from the roots first (they are separate
    scopes, visited on their own by ``iter_scopes``)."""
    body = [s for s in (getattr(scope, "body", []) or [])
            if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))]
    return _common.walk_excluding_defs(body)


def _direct_bindings(node: ast.AST) -> set:
    """Names bound by THIS node's own targets (never descendants — the
    caller walks every node, so counting subtrees would double-count)."""
    out: set = set()
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        return _common.assignment_targets(node)
    if isinstance(node, ast.NamedExpr):
        _common._target_names(node.target, out)
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        _common._target_names(node.target, out)
    elif isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
            if item.optional_vars is not None:
                _common._target_names(item.optional_vars, out)
    elif isinstance(node, (ast.Import, ast.ImportFrom)):
        for alias in node.names:
            out.add((alias.asname or alias.name).split(".")[0])
    return out


def _mesh_bindings(scope, mod) -> Dict[str, Tuple[str, ...]]:
    """name -> axis names, for names whose ONLY binding in ``scope`` is a
    mesh construction with a literal axis_names argument. A name rebound
    anywhere else in the scope — to another mesh OR to anything at all
    (a helper call, an attribute) — is dropped as ambiguous: the rule
    fires only on statically-certain evidence."""
    found: Dict[str, List[Optional[Tuple[str, ...]]]] = {}
    bind_counts: Dict[str, int] = {}
    # a function parameter is a binding too: `def f(mesh=None): if mesh is
    # None: mesh = Mesh(...)` may receive a DIFFERENT mesh from the caller
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = scope.args
        for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            bind_counts[arg.arg] = bind_counts.get(arg.arg, 0) + 1
    for stmt in _scope_walk(scope):
        for bound in _direct_bindings(stmt):
            bind_counts[bound] = bind_counts.get(bound, 0) + 1
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)):
            continue
        if mod.resolve(stmt.value.func) not in _MESH_CTORS:
            continue
        call = stmt.value
        axes_node = None
        if len(call.args) >= 2:
            axes_node = call.args[1]
        for kw in call.keywords:
            if kw.arg == "axis_names":
                axes_node = kw.value
        axes = _axis_names(axes_node) if axes_node is not None else None
        found.setdefault(stmt.targets[0].id, []).append(axes)
    return {
        name: binds[0]
        for name, binds in found.items()
        if len(binds) == 1 and binds[0] is not None
        and bind_counts.get(name, 0) == 1
    }


def _spec_axes(call: ast.Call) -> List[Tuple[str, ast.AST]]:
    """(axis name, node) for every literal string axis in a
    ``PartitionSpec(...)`` call — including ``("a", "b")`` tuple entries
    that shard one dimension over two mesh axes."""
    axes = []
    for arg in call.args:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            axes.append((arg.value, arg))
        elif isinstance(arg, (ast.Tuple, ast.List)):
            for elt in arg.elts:
                if (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)):
                    axes.append((elt.value, elt))
    return axes


class MeshAxisMismatch:
    code = "JG013"
    name = "mesh-axis-mismatch"
    summary = "sharding spec names axes the paired mesh does not have"

    def _check_spec(self, mod, spec_call: ast.Call,
                    mesh_axes: Tuple[str, ...], where: str):
        used: Dict[str, ast.AST] = {}
        for axis, node in _spec_axes(spec_call):
            if axis not in mesh_axes:
                yield mod.finding(
                    self.code,
                    f"{where} names axis {axis!r} but the mesh's axes are "
                    f"{tuple(mesh_axes)!r} — jax will reject this sharding "
                    f"when it is first used, at run time on the chip; "
                    f"rename the axis or fix the mesh",
                    spec_call,
                ), spec_call
            elif axis in used:
                yield mod.finding(
                    self.code,
                    f"{where} uses mesh axis {axis!r} for two dimensions — "
                    f"an axis can shard at most one dimension of one value",
                    spec_call,
                ), spec_call
            else:
                used[axis] = node

    def _spec_calls(self, mod, node: ast.AST) -> List[ast.Call]:
        """Every PartitionSpec(...) call inside ``node`` (covers a lone
        spec, tuples of specs, and nested spec structures)."""
        return [
            n for n in ast.walk(node)
            if isinstance(n, ast.Call)
            and mod.resolve(n.func) in _PSPEC
        ]

    def check(self, mod):
        for scope in _common.iter_scopes(mod.tree):
            meshes = _mesh_bindings(scope, mod)
            if not meshes:
                continue
            for node in _scope_walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                resolved = mod.resolve(node.func)
                if resolved in _NAMED_SHARDING:
                    if not (node.args
                            and isinstance(node.args[0], ast.Name)
                            and node.args[0].id in meshes):
                        continue
                    axes = meshes[node.args[0].id]
                    for spec_arg in node.args[1:]:
                        for spec in self._spec_calls(mod, spec_arg):
                            yield from self._check_spec(
                                mod, spec, axes, "NamedSharding spec")
                elif resolved in _SHARD_MAP:
                    # signature: shard_map(f, mesh, in_specs, out_specs) —
                    # every argument may be positional or keyword
                    mesh_node = None
                    spec_nodes = []
                    if len(node.args) >= 2:
                        mesh_node = node.args[1]
                    if len(node.args) >= 3:
                        spec_nodes.append(("in_specs", node.args[2]))
                    if len(node.args) >= 4:
                        spec_nodes.append(("out_specs", node.args[3]))
                    for kw in node.keywords:
                        if kw.arg == "mesh":
                            mesh_node = kw.value
                        elif kw.arg in ("in_specs", "out_specs"):
                            spec_nodes.append((kw.arg, kw.value))
                    if not (isinstance(mesh_node, ast.Name)
                            and mesh_node.id in meshes):
                        continue
                    axes = meshes[mesh_node.id]
                    for label, spec_node in spec_nodes:
                        for spec in self._spec_calls(mod, spec_node):
                            yield from self._check_spec(
                                mod, spec, axes, f"shard_map {label} spec")
