"""JG020 — synchronous host I/O on a timed train-step path.

The measured stall behind this rule is real and on this tree's books:
checkpoint writes are fsync-bound and synchronous, and on the toy
resilience workload they cost 34% of wall (BENCH_resilience_r01.json) —
the device *idles* while the host writes. The general hazard: a timed
region that drives traced (jit/pmap/shard_map) step work also reaches
``open``/``os.fsync``/``urllib.request.urlopen``/``socket.*`` somewhere
down its call graph, so the step cadence (and every number measured over
it) silently includes host I/O the accelerator cannot overlap.

What makes this a *cross-module* rule: the I/O never sits in the step
loop — it sits in a publish/log/upload helper two calls away. Phase 1's
project index marks which functions perform sync I/O directly and the
rule consults the TRANSITIVE closure (:meth:`ProjectIndex.io_tainted`),
the same machinery JG009 uses for host callbacks.

Scope discipline keeps the tree clean and the findings true: a region
only qualifies as a *train-step* region when it both reads a wall clock
(JG009's two region shapes: a clock-reading loop, or the straight-line
span between two clock reads) AND calls something known to be traced —
a project-index ``traced`` summary, a local ``step = jax.jit(...)``
binding, or an inline ``jax.jit(f)(x)`` (JG015's detection). Deliberate
I/O timing — the store's fsync-bound publish measured *on purpose*, a
bench writing its artifact — has no traced call in the window and stays
silent.

True negatives the fixtures pin: I/O outside any timed region, timed
I/O without step work (the supervisor's ``_publish`` shape), pure
helpers, and reads that are part of the region's *protocol* rather than
its work are not special-cased — move them out or suppress with a
justification (the async-checkpoint ROADMAP item is the real fix).
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common


def _clock_lines(nodes, mod):
    return sorted(
        n.lineno
        for n in _common.walk_excluding_defs(nodes)
        if isinstance(n, ast.Call) and mod.resolve(n.func) in _common.CLOCK_CALLS
    )


class SyncHostIoOnStepPath:
    code = "JG020"
    name = "sync-host-io-on-step-path"
    summary = ("synchronous file/network I/O reachable from a timed "
               "train-step region — the device idles while the host "
               "blocks, and the step measurement includes it")
    skip_tests = True

    def check(self, mod):
        jitted_locals = self._jitted_names(mod)
        reported = set()
        # region 1: any loop that reads a clock
        for loop in _common.iter_loops(mod.tree):
            if _clock_lines(loop, mod):
                calls = [
                    n for n in _common.walk_excluding_defs(loop)
                    if isinstance(n, ast.Call)
                ]
                yield from self._scan(mod, calls, jitted_locals, reported,
                                      where="timed loop")
        # region 2: the straight-line span between the first and last
        # clock read of a function body (nested defs excluded)
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None)
            if not body:
                continue
            lines = _clock_lines(body, mod)
            if len(lines) < 2:
                continue
            lo, hi = lines[0], lines[-1]
            span = [
                n for n in _common.walk_excluding_defs(body)
                if isinstance(n, ast.Call)
                and lo <= getattr(n, "lineno", 0) <= hi
            ]
            yield from self._scan(mod, span, jitted_locals, reported,
                                  where="timed span")

    # -- "train-step": the region must drive traced work -------------------
    def _jitted_names(self, mod):
        names = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if (isinstance(value, ast.Call)
                    and mod.resolve(value.func) in _common.TRACING_WRAPPERS):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        names.add(target.id)
        return names

    def _traced_call(self, call: ast.Call, mod, jitted_locals) -> bool:
        if (isinstance(call.func, ast.Call)
                and mod.resolve(call.func.func) in _common.TRACING_WRAPPERS):
            return True  # inline jax.jit(f)(x)
        if isinstance(call.func, ast.Name) and call.func.id in jitted_locals:
            return True  # step = jax.jit(...); ...; step(x)
        if mod.project is not None:
            summary = mod.project.resolve_function(mod, call.func)
            if summary is not None and summary.traced:
                return True
        return False

    # -- the scan -----------------------------------------------------------
    def _scan(self, mod, calls, jitted_locals, reported, where):
        if not any(self._traced_call(c, mod, jitted_locals) for c in calls):
            return  # timed, but not a train-step region — not ours
        for call in calls:
            if id(call) in reported:
                continue
            resolved = mod.resolve(call.func)
            if resolved in _common.SYNC_IO_CALLS:
                reported.add(id(call))
                f = mod.finding(
                    self.code,
                    f"`{resolved}` inside a {where} that drives traced "
                    f"step work — synchronous host I/O serializes the "
                    f"step cadence (the device idles while the host "
                    f"blocks; the fsync-bound checkpoint write measured "
                    f"34% of wall on the toy workload); move the I/O off "
                    f"the step path (background thread / post-loop)",
                    call,
                )
                yield f, call
                continue
            if (mod.project is None or resolved in _common.CLOCK_CALLS
                    or resolved in _common.HOST_CALLBACKS):
                continue
            summary = mod.project.resolve_function(mod, call.func)
            if summary is not None and mod.project.io_tainted(summary):
                reported.add(id(call))
                f = mod.finding(
                    self.code,
                    f"`{ast.unparse(call.func)}` is called inside a "
                    f"{where} that drives traced step work, and "
                    f"`{summary.fq}` performs synchronous host I/O "
                    f"(open/fsync/urlopen/socket), directly or through "
                    f"its callees — the step measurement includes host "
                    f"I/O the device cannot overlap; move it off the "
                    f"step path or run it on a background thread",
                    call,
                )
                yield f, call
