"""JG001 — PRNG key reuse.

JAX random functions are pure: the same key yields the same stream, so a key
passed to two ``jax.random.*`` draws without an intervening ``split`` /
``fold_in`` silently correlates the draws. In a GAN that is not a crash, it
is a *quality* bug — e.g. z_fake == z_gan would feed the discriminator and
generator phases identical latents forever (the exact class round-2 VERDICT
weak #5 flagged in the fused iteration before ``fold_in``-per-step landed).

Two detections, both scope-local and name-based (no dataflow across calls):

1. straight-line reuse — the same key *expression* (``key``, ``ks[2]``)
   is the key argument of two consuming ``jax.random.*`` calls with no
   rebinding of its base name in between;
2. loop reuse — a consuming call inside a for/while loop whose key
   expression references no name bound in the loop body: every iteration
   replays the same stream (``fid.py``'s per-stage draw is clean precisely
   because its key IS the loop target).

Key-deriving calls (``split``, ``fold_in``, ``PRNGKey``, ...) are not
consumers; subscripted keys are tracked by full expression text, so
``ks[0]`` vs ``ks[1]`` are distinct.
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common

# jax.random functions that DERIVE keys rather than consuming entropy
_NON_CONSUMERS = {
    "split", "fold_in", "PRNGKey", "key", "wrap_key_data", "key_data",
    "clone", "key_impl",
}


def _consumer_name(call: ast.Call, mod) -> str | None:
    resolved = mod.resolve(call.func)
    if not resolved or not resolved.startswith("jax.random."):
        return None
    fn = resolved.rsplit(".", 1)[1]
    if fn in _NON_CONSUMERS:
        return None
    return fn


def _key_arg(call: ast.Call) -> ast.AST | None:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "key":
            return kw.value
    return None


def _stmt_eval_roots(stmt: ast.stmt):
    """The expressions THIS statement evaluates itself. Compound statements
    contribute only their headers — their bodies are scanned by block
    recursion, which owns branch/loop key-tracking semantics."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


def _consumers_in(roots, mod):
    """(call, fn, key_arg) for consuming jax.random calls under ``roots``,
    nested def/lambda bodies excluded."""
    out = []
    for node in _common.walk_excluding_defs(roots):
        if not isinstance(node, ast.Call):
            continue
        fn = _consumer_name(node, mod)
        if fn is None:
            continue
        key = _key_arg(node)
        if key is not None:
            out.append((node, fn, key))
    out.sort(key=lambda t: (t[0].lineno, t[0].col_offset))
    return out


def _stmt_consumers(stmt: ast.stmt, mod):
    return _consumers_in(_stmt_eval_roots(stmt), mod)


class PrngKeyReuse:
    code = "JG001"
    name = "prng-key-reuse"
    summary = ("same PRNG key passed to two jax.random draws without an "
               "intervening split/fold_in")

    def check(self, mod):
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None)
            if not body:
                continue
            yield from self._scan_block(body, {}, mod, scope)

    # -- block scan ---------------------------------------------------------
    def _scan_block(self, stmts, used, mod, scope):
        """``used``: key-expression text -> (first consumer line, fn name)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes handled by iter_scopes
            for call, fn, key in _stmt_consumers(stmt, mod):
                expr = ast.unparse(key)
                if expr in used:
                    first_line, first_fn = used[expr]
                    f = mod.finding(
                        self.code,
                        f"PRNG key `{expr}` already consumed by "
                        f"jax.random.{first_fn} at line {first_line} — "
                        f"split/fold_in before drawing again",
                        call,
                    )
                    yield f, call
                else:
                    used[expr] = (call.lineno, fn)
            # rebinding this statement's targets retires their keys
            rebound = _common.assignment_targets(stmt)
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                _common._target_names(stmt.target, rebound)
            if rebound:
                for expr in [e for e in used
                             if _expr_base(e) in rebound]:
                    del used[expr]
            # recurse into compound statements
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                yield from self._scan_loop(stmt, dict(used), mod, scope)
            elif isinstance(stmt, ast.If):
                yield from self._scan_block(stmt.body, dict(used), mod, scope)
                yield from self._scan_block(stmt.orelse, dict(used), mod, scope)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._scan_block(stmt.body, used, mod, scope)
            elif isinstance(stmt, ast.Try):
                for block in (stmt.body, stmt.orelse, stmt.finalbody):
                    yield from self._scan_block(block, used, mod, scope)
                for handler in stmt.handlers:
                    yield from self._scan_block(handler.body, dict(used),
                                                mod, scope)

    def _scan_loop(self, loop, used, mod, scope):
        """Straight-line reuse inside the body, plus the loop-replay check:
        a consumer whose key derives from nothing bound per-iteration."""
        yield from self._scan_block(loop.body, used, mod, scope)
        loop_bound = _common.bound_names(loop)
        for call, fn, key in _consumers_in(loop.body, mod):
            if not (_common.loaded_names(key) & loop_bound):
                expr = ast.unparse(key)
                f = mod.finding(
                    self.code,
                    f"PRNG key `{expr}` consumed by jax.random.{fn} "
                    f"inside a loop but derived outside it — every "
                    f"iteration replays the same stream; fold_in the "
                    f"loop index",
                    call,
                )
                yield f, call


def _expr_base(expr_text: str) -> str:
    for i, ch in enumerate(expr_text):
        if not (ch.isalnum() or ch == "_"):
            return expr_text[:i]
    return expr_text
