"""JG028 — unbalanced release: double-close or close-without-open.

The dual of JG027: instead of a close that can be skipped, a close that
can run *twice* (releasing a lock another thread now holds — on
``threading.Lock`` a ``RuntimeError``, on a semaphore a silently grown
permit pool) or run with nothing open (a refund without a take inflates
the budget; a ``-=`` without the ``+=`` drives the in-flight ledger
negative, which is how the PR 4 ledger corrupted). The loop variant is
the sneakiest: a single open before a loop with the close inside the
body releases once per iteration.

The model (phase-1½ lifecycle index, balance pass): a per-receiver
open/closed state machine over straight-line blocks. A close in the
``closed`` state is a **double-close**; a close when only *some*
preceding branch opened (the maybe-open join state) is a
**close-without-open** on the branch that didn't; a close inside a loop
body for a resource opened outside the loop is a **loop-carried
release**. The machine resets to unknown at joins it cannot follow
(loops over the whole pair, cross-function halves), so only statically
certain shapes are flagged.

Not flagged: close-then-reopen sequences (the state machine tracks
order); branch-exits (``if ...: close(); return`` followed by a second
close on the surviving path — the first path already left); the partial
close of an ``if``/``else`` where the *other* arm leaks (that is
JG027's finding, not a balance defect). Known false negatives: halves
split across helper calls; receiver aliasing (``lk = self._lock``
closed via both names).
"""

from __future__ import annotations


class UnbalancedRelease:
    code = "JG028"
    name = "unbalanced-release"
    summary = ("double-close or close-without-open on some path, "
               "including loop-carried releases")
    skip_tests = True

    def check(self, mod):
        if mod.project is None:
            return
        for fl in mod.project.lifecycle.functions(mod.path):
            for issue in fl.issues:
                closer = (f"`{issue.recv} -= ...`"
                          if issue.pair.kind == "counter"
                          else f"`{issue.recv}.{issue.pair.close}()`")
                if issue.kind == "double-close":
                    msg = (f"`{fl.name}` closes {closer} twice on one "
                           f"path — the second release frees a resource "
                           f"this frame no longer owns (another taker may "
                           f"already hold it); close exactly once per "
                           f"open")
                elif issue.kind == "close-without-open":
                    msg = (f"`{fl.name}` reaches {closer} on a path where "
                           f"the matching `{issue.pair.open}` never ran — "
                           f"the unconditional close after a conditional "
                           f"open over-releases; mirror the condition or "
                           f"close inside the branch that opened")
                else:  # loop-carried-release
                    msg = (f"`{fl.name}` closes {closer} inside a loop "
                           f"body for an open made outside the loop — "
                           f"zero iterations never release it and N "
                           f"iterations release it N times; move the "
                           f"close out of the loop")
                yield mod.finding(self.code, msg, issue.node), issue.node
