"""JG023 — alert rule naming a metric family the tree never creates.

The alerting plane (telemetry/alerts.py, docs/OBSERVABILITY.md
"Alerting") is declarative: an :class:`AlertRule` names the metric
family it evaluates as a string. That string is looked up in a snapshot
dict at runtime — so a typo does not error, it makes the rule evaluate
over a family that is never there. A ``threshold``/``burn``/``anomaly``
rule then sees no series and sits at undefined/pending forever (the
fail-closed design hides the typo perfectly), and an ``absence`` rule
fires forever on a family that was never going to exist. Either way the
alert an operator thinks they have is not the alert they have — the
exact silent-typo failure mode a static check can kill.

The rule: every **literal** metric name passed to an ``AlertRule``
construction (the ``metric=`` keyword or its positional slot) must
resolve against the set of metric families the analyzed tree actually
creates:

- literal first arguments of ``<registry>.counter(...)`` /
  ``.gauge(...)`` / ``.histogram(...)`` calls anywhere in the project
  index (the one get-or-create surface every family goes through);
- f-string family names (``f"{metric_prefix}_slo_burn_rate"`` — the
  SLOTracker's prefix-scoped gauges) matched as wildcard patterns, so
  ``fleet_slo_burn_rate`` and ``mux_slo_burn_rate`` both resolve;
- module-level UPPER_CASE string constants that look like metric names
  (``MEMBER_UP = "fleet_member_up"`` — the aggregate module's
  synthesized families are declared this way).

Non-literal metrics (variables, computed names) are out of scope —
silence, not a guess. True negatives: rules naming any family the tree
creates (directly, via an f-string pattern, or via a declared
constant), and test modules (``skip_tests`` — fixture rules point at
fixture metrics on purpose).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional, Set, Tuple

from gan_deeplearning4j_tpu.analysis import _common

#: the registry's get-or-create family methods
_FAMILY_METHODS = {"counter", "gauge", "histogram"}

#: shapes that read as a metric family name (prom-ish snake_case)
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*_[a-z0-9_]*$")


def _family_literals(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """``(exact, patterns)`` of family names one module creates: exact
    string literals, and regex sources for f-string names (formatted
    fields become ``.*``)."""
    exact: Set[str] = set()
    patterns: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _FAMILY_METHODS and node.args):
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(
                    first.value, str):
                exact.add(first.value)
            elif isinstance(first, ast.JoinedStr):
                parts = []
                for piece in first.values:
                    if isinstance(piece, ast.Constant):
                        parts.append(re.escape(str(piece.value)))
                    else:
                        parts.append(".*")
                patterns.add("^" + "".join(parts) + "$")
        elif isinstance(node, ast.Assign):
            # module-level ALL_CAPS string constants declaring synthetic
            # family names (aggregate.MEMBER_UP)
            value = node.value
            if not (isinstance(value, ast.Constant)
                    and isinstance(value.value, str)
                    and _METRIC_NAME_RE.match(value.value)):
                continue
            for target in node.targets:
                if (isinstance(target, ast.Name)
                        and target.id.isupper()):
                    exact.add(value.value)
    return exact, patterns


def _known_families(mod) -> Tuple[Set[str], Set[str]]:
    """Every family the analyzed tree creates — the whole project index
    when phase 1 ran, this module alone otherwise."""
    exact: Set[str] = set()
    patterns: Set[str] = set()
    index = getattr(mod, "project", None)
    trees: Iterable[ast.AST]
    if index is not None and getattr(index, "modules", None):
        trees = (info.srcmod.tree for info in index.modules.values()
                 if info.srcmod is not None)
    else:
        trees = (mod.tree,)
    for tree in trees:
        e, p = _family_literals(tree)
        exact |= e
        patterns |= p
    return exact, patterns


def _rule_metric(call: ast.Call) -> Optional[ast.Constant]:
    """The literal ``metric`` argument of an AlertRule construction —
    keyword or positional slot 2 (name, kind, metric) — or None when it
    is absent/non-literal (out of scope)."""
    node: Optional[ast.AST] = None
    for kw in call.keywords:
        if kw.arg == "metric":
            node = kw.value
            break
    else:
        if len(call.args) > 2:
            node = call.args[2]
    if (isinstance(node, ast.Constant) and isinstance(node.value, str)
            and node.value):
        return node
    return None


class UnknownMetricInAlertRule:
    code = "JG023"
    name = "unknown-metric-in-alert-rule"
    summary = ("alert rule names a metric family the tree never creates — "
               "the rule silently evaluates nothing forever")
    skip_tests = True

    def check(self, mod):
        rules = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = _common.resolve_call(node, mod.imports) or ""
            if resolved.split(".")[-1] != "AlertRule":
                continue
            metric = _rule_metric(node)
            if metric is not None:
                rules.append((node, metric))
        if not rules:
            return
        exact, patterns = _known_families(mod)
        compiled = [re.compile(p) for p in patterns]
        for call, metric in rules:
            name = metric.value
            if name in exact:
                continue
            if any(p.match(name) for p in compiled):
                continue
            yield mod.finding(
                self.code,
                f"alert rule names metric {name!r}, but no registry "
                f"family with that name is created anywhere in the "
                f"analyzed tree — a threshold/burn/anomaly rule over it "
                f"evaluates nothing forever and an absence rule fires "
                f"forever; fix the name or create the family",
                metric,
            ), call
