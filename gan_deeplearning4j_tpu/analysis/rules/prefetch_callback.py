"""JG019 — host callback reached from a prefetch/data-pipeline callback
consumed inside a timed region.

JG009 catches a host callback the timed loop CALLS — directly or through
the call graph. It cannot catch the indirect shape the streaming input
pipeline introduces: a callable handed to a prefetch/pipeline object at
CONSTRUCTION (``DevicePrefetchIterator(inner, transform=log_row)``) fires
later, from inside ``next()``/``has_next()`` refills, while the training
window is being timed — the loop's own call graph never mentions the
callback, so JG009 is structurally blind to it. The measured symptom is
identical (a ~70 ms host round-trip billed to the step time, PROFILE.md
round 3) but the edit distance is worse: the offending line is the
pipeline construction, screens away from the loop it poisons.

The rule is scope-local over the construction and flow-free on purpose:

1. a *pipeline construction* is a call whose callee's terminal identifier
   contains ``prefetch`` or ``pipeline`` (case-insensitive; the repo seam
   is :class:`~gan_deeplearning4j_tpu.data.iterator.DevicePrefetchIterator`
   and its ``transform=`` hook), assigned whole to one name;
2. a *tainted callback* among its arguments is a lambda literal whose body
   performs a host callback, or a name whose function def (same module)
   reaches one — directly or through the project index's transitive
   callback taint;
3. a *timed region* is JG009's: a loop that reads a wall clock, or the
   span between a function body's first and last clock reads;
4. the finding fires where the tainted pipeline is CONSUMED inside a
   timed region — a method call on the variable (``it.next()``,
   ``it.next_window(k)``), iteration over it (``for batch in it:`` or a
   comprehension), or the variable passed into another call
   (``run(exp, it)``).

True negatives: pure host-side transforms (numpy math), tainted pipelines
consumed only outside timed regions, pipeline constructors with no
function-valued arguments, and host callbacks invoked directly by the
loop (JG009's finding, not this rule's).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from gan_deeplearning4j_tpu.analysis import _common
from gan_deeplearning4j_tpu.analysis.rules.callbacks import _clock_lines

_SEAM_TOKENS = ("prefetch", "pipeline")


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class PrefetchCallbackInTimedRegion:
    code = "JG019"
    name = "prefetch-callback-in-timed-region"
    summary = ("host callback reached from a prefetch/data-pipeline "
               "callback consumed inside a timed region")

    # -- taint ------------------------------------------------------------
    def _direct_callback(self, mod, body) -> bool:
        for n in ast.walk(body) if isinstance(body, ast.AST) else body:
            if isinstance(n, ast.Call) \
                    and mod.resolve(n.func) in _common.HOST_CALLBACKS:
                return True
        return False

    def _tainted_callable(self, mod, defs: Dict[str, ast.AST],
                          node: ast.AST) -> bool:
        """Is this argument a function value that reaches a host callback?"""
        if isinstance(node, ast.Lambda):
            return self._direct_callback(mod, node.body)
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        if name is not None and name in defs:
            fn = defs[name]
            if self._direct_callback(mod, fn):
                return True
        # transitive: the project index's callback taint closure covers
        # helpers-of-helpers and cross-module callbacks
        if mod.project is not None and isinstance(
                node, (ast.Name, ast.Attribute)):
            summary = mod.project.resolve_function(mod, node)
            if summary is not None and mod.project.callback_tainted(summary):
                return True
        return False

    def _local_defs(self, mod) -> Dict[str, ast.AST]:
        """name -> def/lambda node for every function defined in the
        module (including ``f = lambda ...`` binds)."""
        defs: Dict[str, ast.AST] = {}
        for n in ast.walk(mod.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(n.name, n)
            elif (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and isinstance(n.value, ast.Lambda)):
                defs.setdefault(n.targets[0].id, n.value)
        return defs

    def _tainted_pipelines(self, mod) -> Dict[str, ast.Call]:
        """var name -> construction call, for every pipeline built with a
        callback that reaches a host callback."""
        defs = self._local_defs(mod)
        out: Dict[str, ast.Call] = {}
        for n in ast.walk(mod.tree):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and isinstance(n.value, ast.Call)):
                continue
            callee = _terminal(n.value.func)
            if callee is None or not any(
                    tok in callee.lower() for tok in _SEAM_TOKENS):
                continue
            for _, arg in _common.call_args_with_keywords(n.value):
                if self._tainted_callable(mod, defs, arg):
                    out[n.targets[0].id] = n.value
                    break
        return out

    # -- regions (JG009's shapes) -----------------------------------------
    def _regions(self, mod):
        for loop in _common.iter_loops(mod.tree):
            if _clock_lines(loop, mod):
                yield "timed loop", list(_common.walk_excluding_defs(loop))
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None)
            if not body:
                continue
            lines = _clock_lines(body, mod)
            if len(lines) < 2:
                continue
            lo, hi = lines[0], lines[-1]
            yield "timed span", [
                n for n in _common.walk_excluding_defs(body)
                if lo <= getattr(n, "lineno", 0) <= hi
            ]

    def check(self, mod):
        pipelines = self._tainted_pipelines(mod)
        if not pipelines:
            return
        flagged = set()  # one finding per pipeline variable: the defect
        # is the construction, however many consumption sites it has
        for where, nodes in self._regions(mod):
            for call in nodes:
                var = None
                if isinstance(call, (ast.For, ast.AsyncFor)) and isinstance(
                        call.iter, ast.Name) and call.iter.id in pipelines:
                    # the iterator protocol: `for batch in it:` — the most
                    # idiomatic consumption of the seam
                    var = call.iter.id
                elif isinstance(call, (ast.GeneratorExp, ast.ListComp,
                                       ast.SetComp, ast.DictComp)):
                    for gen in call.generators:
                        if isinstance(gen.iter, ast.Name) \
                                and gen.iter.id in pipelines:
                            var = gen.iter.id
                            break
                elif isinstance(call, ast.Call):
                    # it.next() / it.has_next() / it.next_window(k)
                    if isinstance(call.func, ast.Attribute) and isinstance(
                            call.func.value, ast.Name) \
                            and call.func.value.id in pipelines:
                        var = call.func.value.id
                    else:
                        # the pipeline handed to a consumer: run(exp, it)
                        for _, arg in _common.call_args_with_keywords(call):
                            if isinstance(arg, ast.Name) \
                                    and arg.id in pipelines:
                                var = arg.id
                                break
                if var is None or var in flagged:
                    continue
                flagged.add(var)
                ctor = pipelines[var]
                # anchored at the CONSTRUCTION — the actionable line, and
                # a stable anchor however many consumption sites exist
                yield mod.finding(
                    self.code,
                    f"`{var}` is consumed inside a {where} (line "
                    f"{call.lineno}), and its construction installs a "
                    f"callback that performs a host callback "
                    f"(io_callback/pure_callback/jax.debug.*) — every "
                    f"prefetch refill round-trips through the host inside "
                    f"the measurement (~70 ms through the tunnel); strip "
                    f"the callback or move the pipeline's timed "
                    f"consumption out of the clocked region",
                    ctor,
                ), ctor
