"""JG015 — unfenced wall-clock delta fed to a telemetry sink.

The telemetry plane (docs/OBSERVABILITY.md) makes it one line to record a
duration: ``hist.observe(time.perf_counter() - t0)``,
``stats.add("device", dt)``. That convenience revives the repo's oldest
measurement bug in a new place: XLA dispatch is ASYNCHRONOUS, so a
perf-counter delta taken around a jitted call without a device fence
measures dispatch latency, not execution — and unlike a wrong log line, a
wrong histogram is *load-bearing*: it lands in ``/metrics``, Prometheus
scrapes, BENCH artifacts, and the routing/reload decisions built on them.
JG002 polices stale fences in timed loops; this rule extends the same
fence analysis to the telemetry API: a clock delta that (a) brackets a
call known to be jit/pmap/shard_map-traced (project-index summaries, a
local ``f = jax.jit(...)`` binding, or a direct ``jax.jit(fn)(x)``),
(b) reaches a metrics sink (``.observe(...)``/``.add(...)``/
``.record(...)``/``.set(...)``), and (c) sees no fence on the traced
call's output (``block_until_ready``, ``device_get``, ``np.asarray``,
``.item()``) between the call and the second clock read — is flagged.

True negatives the fixtures pin: fenced deltas (the PhaseTimer sink-list
idiom), deltas around non-traced work (the store's fsync-bound publish),
and deltas that only land in plain dicts/lists (summaries are not
scrape sinks — JG009/JG002 own the general cases).
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Set

from gan_deeplearning4j_tpu.analysis import _common

_CLOCKS = _common.CLOCK_CALLS
_SINK_METHODS = {"observe", "add", "record", "set"}
_FENCE_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
    "jax.block_until_ready", "jax.device_get",
}
_FENCE_METHODS = {"block_until_ready", "item"}


def _is_clock_call(node, mod) -> bool:
    return (isinstance(node, ast.Call)
            and mod.resolve(node.func) in _CLOCKS)


def _clock_delta_names(expr: ast.AST, clock_names: Set[str], mod
                       ) -> Optional[Set[str]]:
    """The t0-style names a ``clock() - t0`` expression closes over, or
    None when ``expr`` is not a clock delta."""
    if not isinstance(expr, ast.BinOp) or not isinstance(expr.op, ast.Sub):
        return None
    if not _is_clock_call(expr.left, mod):
        return None
    read = _common.loaded_names(expr.right) & clock_names
    return read or None


def _fence_read_names(call: ast.Call, mod) -> Optional[Set[str]]:
    resolved = mod.resolve(call.func)
    if resolved in _FENCE_CALLS:
        names: Set[str] = set()
        for arg in call.args:
            names |= _common.loaded_names(arg)
        return names
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in _FENCE_METHODS and not call.args):
        return _common.loaded_names(call.func.value)
    return None


class TelemetryUnfencedTiming:
    code = "JG015"
    name = "telemetry-unfenced-timing"
    summary = ("clock delta around a jitted call feeds a telemetry sink "
               "without a device fence — the metric records dispatch, "
               "not execution")
    skip_tests = True

    def check(self, mod):
        jitted_locals = self._jitted_names(mod)
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None)
            if not body:
                continue
            yield from self._check_scope(mod, body, jitted_locals)

    # -- what counts as "a jitted call" -------------------------------------
    def _jitted_names(self, mod) -> Set[str]:
        """Names bound (anywhere in the module) to the result of a tracing
        wrapper: ``step = jax.jit(fn)`` — callable later as ``step(x)``."""
        names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if (isinstance(value, ast.Call)
                    and mod.resolve(value.func) in _common.TRACING_WRAPPERS):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        names.add(target.id)
        return names

    def _traced_call(self, call: ast.Call, mod, jitted_locals) -> bool:
        # direct jax.jit(fn)(x)
        if (isinstance(call.func, ast.Call)
                and mod.resolve(call.func.func) in _common.TRACING_WRAPPERS):
            return True
        if isinstance(call.func, ast.Name) and call.func.id in jitted_locals:
            return True
        if mod.project is not None:
            summary = mod.project.resolve_function(mod, call.func)
            if summary is not None and summary.traced:
                return True
        return False

    # -- the per-scope dataflow ---------------------------------------------
    def _check_scope(self, mod, body, jitted_locals):
        # walk the scope once, excluding nested defs (their own scopes)
        nodes = list(_common.walk_excluding_defs(body))
        calls = [n for n in nodes if isinstance(n, ast.Call)]

        # 1. clock origin assignments: t0 = time.perf_counter()
        clock_names: Dict[str, int] = {}
        for n in nodes:
            if isinstance(n, ast.Assign) and _is_clock_call(n.value, mod):
                for target in n.targets:
                    if isinstance(target, ast.Name):
                        clock_names[target.id] = n.lineno

        # 2. delta bindings: dt = clock() - t0 (the sink may consume the
        #    name instead of the expression)
        delta_vars: Dict[str, ast.AST] = {}
        for n in nodes:
            if isinstance(n, ast.Assign):
                if _clock_delta_names(n.value, set(clock_names), mod):
                    for target in n.targets:
                        if isinstance(target, ast.Name):
                            delta_vars[target.id] = n.value

        # 3. sink calls whose argument is a clock delta (inline or named)
        for call in calls:
            if not (isinstance(call.func, ast.Attribute)
                    and call.func.attr in _SINK_METHODS):
                continue
            for arg in call.args:
                delta = None
                if _clock_delta_names(arg, set(clock_names), mod):
                    delta = arg
                elif (isinstance(arg, ast.Name) and arg.id in delta_vars):
                    delta = delta_vars[arg.id]
                if delta is None:
                    continue
                origins = _clock_delta_names(delta, set(clock_names), mod)
                t0_line = min(clock_names[name] for name in origins)
                finding = self._judge(
                    mod, calls, call, delta, t0_line, jitted_locals)
                if finding is not None:
                    yield finding

    def _judge(self, mod, calls, sink, delta, t0_line, jitted_locals):
        """Flag when a traced call sits inside the [t0, delta] window with
        no fence on its output before the window closes."""
        end_line = delta.lineno
        traced = [
            c for c in calls
            if t0_line < c.lineno <= end_line
            and self._traced_call(c, mod, jitted_locals)
        ]
        if not traced:
            return None
        # names bound from the traced calls — what a fence must read
        out_names: Set[str] = set()
        for c in traced:
            out_names |= self._bound_from(c, mod)
        for c in calls:
            # the fence must land BEFORE the second clock read (end_line):
            # a fence after the delta is computed cannot un-poison it, even
            # if it runs before the sink call
            if not t0_line < c.lineno <= end_line:
                continue
            read = _fence_read_names(c, mod)
            if read is None:
                continue
            if (read & out_names) or any(
                    t in ast.walk(c) for t in traced):
                return None  # fenced: np.asarray(out) / jitted call inline
        call_text = ast.unparse(traced[0].func)[:40]
        return mod.finding(
            self.code,
            f"`{ast.unparse(sink.func)[:48]}` records a wall-clock delta "
            f"taken around the jitted call `{call_text}(...)` with no "
            f"device fence on its output — XLA dispatch is async, so the "
            f"metric measures dispatch, not execution; fence with "
            f"`jax.block_until_ready(...)`/`np.asarray(...)` before the "
            f"second clock read (JG002's contract, extended to telemetry "
            f"sinks)",
            sink,
        ), sink

    def _bound_from(self, call: ast.Call, mod) -> Set[str]:
        """Names the statement containing ``call`` assigns — via the parent
        links the engine's SourceModule provides (fallback: empty)."""
        stmt = getattr(call, "_jg_stmt", None)
        if stmt is None:
            # resolve lazily: scan the module for the assignment whose value
            # subtree contains this call
            for n in ast.walk(mod.tree):
                if isinstance(n, ast.Assign) and any(
                        c is call for c in ast.walk(n.value)):
                    stmt = n
                    break
            call._jg_stmt = stmt if stmt is not None else False
        if not stmt:
            return set()
        return _common.assignment_targets(stmt)
