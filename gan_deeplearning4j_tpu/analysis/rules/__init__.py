"""jaxlint rule registry.

Each rule is a small object with ``code``, ``name``, ``summary`` and a
``check(mod) -> iterable`` yielding :class:`~..engine.Finding` (or
``(finding, node)`` tuples when multi-line suppression spans matter).
``skip_tests = True`` exempts test modules (tests legitimately assert).

The catalogue, with the real bug behind each rule, lives in
``docs/STATIC_ANALYSIS.md``. New rules: add a module here, register the
instance in RULES, and give it true-positive/true-negative fixtures in
``tests/test_analysis.py`` — a rule without a fixture proving it fires on
the bug it was derived from is not a rule, it is a hope.
"""

from gan_deeplearning4j_tpu.analysis.rules.prng import PrngKeyReuse
from gan_deeplearning4j_tpu.analysis.rules.timing import StaleFenceTiming
from gan_deeplearning4j_tpu.analysis.rules.asserts import BareAssert
from gan_deeplearning4j_tpu.analysis.rules.recompile import RecompilationHazard
from gan_deeplearning4j_tpu.analysis.rules.host_sync import HostSyncInTracedCode
from gan_deeplearning4j_tpu.analysis.rules.donation import DonationSafety
from gan_deeplearning4j_tpu.analysis.rules.at_update import DiscardedAtUpdate
from gan_deeplearning4j_tpu.analysis.rules.scan_dtype import ScanCarryDtypeDrift
from gan_deeplearning4j_tpu.analysis.rules.callbacks import CallbackInTimedRegion
from gan_deeplearning4j_tpu.analysis.rules.donation_flow import DonationFlow
from gan_deeplearning4j_tpu.analysis.rules.axes import AxisSizeMismatch
from gan_deeplearning4j_tpu.analysis.rules.sharding import DeadDonatedOutSharding
from gan_deeplearning4j_tpu.analysis.rules.mesh_axes import MeshAxisMismatch
from gan_deeplearning4j_tpu.analysis.rules.prng_flow import CrossModulePrngReuse
from gan_deeplearning4j_tpu.analysis.rules.telemetry_fence import (
    TelemetryUnfencedTiming,
)
from gan_deeplearning4j_tpu.analysis.rules.engine_swap import (
    SwapSeamUnguardedAccess,
)
from gan_deeplearning4j_tpu.analysis.rules.net_timeout import (
    UnboundedNetworkCall,
)
from gan_deeplearning4j_tpu.analysis.rules.state_spec import (
    ShardedStateSpecMismatch,
)
from gan_deeplearning4j_tpu.analysis.rules.prefetch_callback import (
    PrefetchCallbackInTimedRegion,
)
from gan_deeplearning4j_tpu.analysis.rules.step_io import (
    SyncHostIoOnStepPath,
)
from gan_deeplearning4j_tpu.analysis.rules.respawn import (
    UnboundedRespawnLoop,
)
from gan_deeplearning4j_tpu.analysis.rules.mux_sharing import (
    CrossGenerationEngineSharing,
)
from gan_deeplearning4j_tpu.analysis.rules.alert_metrics import (
    UnknownMetricInAlertRule,
)
from gan_deeplearning4j_tpu.analysis.rules.shared_state import (
    UnguardedSharedMutableState,
)
from gan_deeplearning4j_tpu.analysis.rules.quant_dtype import (
    QuantPrecisionCastMismatch,
)
from gan_deeplearning4j_tpu.analysis.rules.lock_order import (
    LockOrderInversion,
)
from gan_deeplearning4j_tpu.analysis.rules.lock_blocking import (
    BlockingCallUnderLock,
)
from gan_deeplearning4j_tpu.analysis.rules.resource_leak import (
    LeakedPairedResource,
)
from gan_deeplearning4j_tpu.analysis.rules.release_balance import (
    UnbalancedRelease,
)
from gan_deeplearning4j_tpu.analysis.rules.handoff import (
    HandoffWithoutTransfer,
)
from gan_deeplearning4j_tpu.analysis.rules.ladder_literal import (
    HardcodedLadderLiteral,
)
from gan_deeplearning4j_tpu.analysis.rules.double_buffer import (
    DoubleBufferMisuse,
)

RULES = [
    PrngKeyReuse(),
    StaleFenceTiming(),
    BareAssert(),
    RecompilationHazard(),
    HostSyncInTracedCode(),
    DonationSafety(),
    DiscardedAtUpdate(),
    ScanCarryDtypeDrift(),
    CallbackInTimedRegion(),
    DonationFlow(),
    AxisSizeMismatch(),
    DeadDonatedOutSharding(),
    MeshAxisMismatch(),
    CrossModulePrngReuse(),
    TelemetryUnfencedTiming(),
    SwapSeamUnguardedAccess(),
    UnboundedNetworkCall(),
    ShardedStateSpecMismatch(),
    PrefetchCallbackInTimedRegion(),
    SyncHostIoOnStepPath(),
    UnboundedRespawnLoop(),
    CrossGenerationEngineSharing(),
    UnknownMetricInAlertRule(),
    UnguardedSharedMutableState(),
    LockOrderInversion(),
    BlockingCallUnderLock(),
    LeakedPairedResource(),
    UnbalancedRelease(),
    HandoffWithoutTransfer(),
    QuantPrecisionCastMismatch(),
    HardcodedLadderLiteral(),
    DoubleBufferMisuse(),
]

RULES_BY_CODE = {r.code: r for r in RULES}

__all__ = ["RULES", "RULES_BY_CODE"]
