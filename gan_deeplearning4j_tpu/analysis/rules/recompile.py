"""JG004 — recompilation hazards.

``jax.jit`` caches compiled programs on the *callable object* plus static
argument values. Two mechanical ways this repo could (and related repos do)
defeat the cache:

1. jit-in-loop — calling ``jax.jit(...)`` (or decorating a def) inside a
   for/while body constructs a FRESH traced callable every iteration: every
   call retraces and recompiles. On the tunneled axon platform one XLA
   compile is seconds-to-minutes (bench.py measured 70-140 s scan compiles
   on CPU), so this turns a training loop into a compile loop. The jitted
   callable belongs outside the loop (this repo's ``_build_*`` idiom).

2. unhashable static argument — passing a list/dict/set (or a comprehension)
   at a ``static_argnums`` position raises ``TypeError: unhashable`` at
   best; a fresh hashable object of unstable identity recompiles per call.
   Statically visible container literals at known-static positions are
   flagged.
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common

_JIT_NAMES = {"jax.jit", "jax.pmap"}
_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp, ast.GeneratorExp)


def _is_jit_call(node: ast.AST, mod) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = mod.resolve(node.func)
    if resolved in _JIT_NAMES:
        return True
    # functools.partial(jax.jit, ...)
    if resolved == "functools.partial" and node.args:
        return mod.resolve(node.args[0]) in _JIT_NAMES
    return False


def _static_argnums(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            return _common.literal_int_tuple(kw.value)
    return None


class RecompilationHazard:
    code = "JG004"
    name = "recompilation-hazard"
    summary = "jit constructed per-iteration or unhashable static argument"

    def check(self, mod):
        yield from self._check_jit_in_loop(mod)
        yield from self._check_static_args(mod)

    def _check_jit_in_loop(self, mod):
        seen = set()
        for loop in _common.iter_loops(mod.tree):
            for n in ast.walk(loop):
                if n is loop or id(n) in seen:
                    continue
                if _is_jit_call(n, mod):
                    seen.add(id(n))
                    f = mod.finding(
                        self.code,
                        "jax.jit called inside a loop — constructs a fresh "
                        "traced callable (and a fresh compile) every "
                        "iteration; build the jitted function once, outside",
                        n,
                    )
                    yield f, n
                elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in n.decorator_list:
                        target = dec.func if isinstance(dec, ast.Call) else dec
                        if (mod.resolve(target) in _JIT_NAMES
                                or _is_jit_call(dec, mod)) \
                                and id(n) not in seen:
                            seen.add(id(n))
                            f = mod.finding(
                                self.code,
                                f"function `{n.name}` is defined and jitted "
                                f"inside a loop — every iteration compiles "
                                f"a new program; hoist the definition out",
                                n,
                            )
                            yield f, n

    def _check_static_args(self, mod):
        """Track ``name = jax.jit(f, static_argnums=...)`` per scope, then
        flag container literals at static positions of ``name(...)`` calls."""
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None)
            if not body:
                continue
            static_by_name = {}
            for stmt in body:
                if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and _is_jit_call(stmt.value, mod)):
                    nums = _static_argnums(stmt.value)
                    if nums:
                        static_by_name[stmt.targets[0].id] = nums
            if not static_by_name:
                continue
            for n in ast.walk(scope):
                if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                        and n.func.id in static_by_name):
                    for pos in static_by_name[n.func.id]:
                        if pos < len(n.args) and isinstance(
                                n.args[pos], _UNHASHABLE):
                            f = mod.finding(
                                self.code,
                                f"unhashable {type(n.args[pos]).__name__} "
                                f"literal at static_argnums position {pos} "
                                f"of `{n.func.id}` — static args must be "
                                f"hashable and stable, or every call "
                                f"recompiles",
                                n.args[pos],
                            )
                            yield f, n
