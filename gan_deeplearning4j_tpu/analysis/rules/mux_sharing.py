"""JG022 — cross-generation engine table touched outside the registry lock.

The multiplexing plane (serving/mux, docs/MULTIPLEX.md) holds N serving
generations in one variant table: ``registry._variants`` maps a name to
its engine + micro-batcher *while resident*. Unlike the single-model swap
seam (JG016), the table's membership itself is concurrent state — the
residency budget demotes a variant's engine to a cold manifest, a ramp
rollback rewrites weights, and the reload plane adopts new variants, all
from other threads. Reading another generation's engine straight out of
the table (``registry.variants["gen-12"].engine.dispatch(...)``,
``for v in self._variants.values(): v.engine...``) races those
transitions: the engine can be demoted (its batcher closed, its staging
buffers recycled through the shared pool) between the lookup and the
use, which finalizes foreign buffers and releases phantom replica
reservations — the same corruption class JG016 polices, multiplied by N
generations.

The rule: any load of an attribute named like a variant/engine table
(``variants``/``_variants``/``engines``/``_engines``) must sit inside a
``with`` block whose context expression is a lock-ish attribute
(name containing "lock", or a condition-variable name) of the SAME base
object — ``with registry.lock:`` guards ``registry.variants``, ``with
self.lock:`` guards ``self._variants``. Two conventions are exempt:

- ``__init__`` (construction is single-threaded by contract, as in
  JG016), and
- functions whose name ends in ``_locked`` (the caller-holds-the-lock
  helper convention the registry itself uses).

True negatives: access under the matching lock, the exempt conventions
above, locals snapshotted under the lock and used outside it, and
same-named attributes on objects whose lock IS held (the base-expression
match is exact, so ``with a.lock:`` does not bless ``b.variants``)."""

from __future__ import annotations

import ast

#: attribute names that read as "the cross-generation table"
_TABLE_NAMES = {"variants", "_variants", "engines", "_engines"}

#: with-context attribute names that count as a lock (JG016's set)
_LOCK_NAMES = {"_cv", "cv", "_cond", "cond", "_condition", "condition",
               "_mutex", "mutex"}


def _lock_base(expr: ast.AST):
    """``<base>.<lock-ish>`` context expression -> the dump of ``<base>``
    (the guard identity); None for anything else."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
        if "lock" in name.lower() or name in _LOCK_NAMES:
            return ast.dump(expr.value)
    return None


def _expr_src(expr: ast.AST) -> str:
    try:
        return ast.unparse(expr)
    except Exception:  # pragma: no cover - unparse handles all exprs here
        return "<expr>"


class CrossGenerationEngineSharing:
    code = "JG022"
    name = "unguarded-cross-generation-engine-sharing"
    summary = ("cross-generation engine/variant table accessed outside "
               "the registry lock")
    skip_tests = True

    def check(self, mod):
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__" or fn.name.endswith("_locked"):
                continue
            yield from self._scan(mod, fn)

    def _scan(self, mod, fn):
        hits = []

        def visit(node: ast.AST, guarded: frozenset) -> None:
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node is not fn):
                # nested defs get their own scan with a fresh guard set —
                # a closure does not inherit the lexical lock (it may run
                # on another thread, after the with block exited)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = set(guarded)
                for item in node.items:
                    base = _lock_base(item.context_expr)
                    if base is not None:
                        inner.add(base)
                    visit(item.context_expr, guarded)
                inner = frozenset(inner)
                for child in node.body:
                    visit(child, inner)
                return
            if (isinstance(node, ast.Attribute)
                    and node.attr in _TABLE_NAMES
                    and ast.dump(node.value) not in guarded):
                hits.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child, guarded)

        for stmt in fn.body:
            visit(stmt, frozenset())
        for node in hits:
            base = _expr_src(node.value)
            yield mod.finding(
                self.code,
                f"`{fn.name}` reads the cross-generation engine table "
                f"`{base}.{node.attr}` outside the registry lock — the "
                f"residency budget, a ramp rollback, or a reload adoption "
                f"can demote/evict an engine between the lookup and the "
                f"use (foreign staging buffers recycled, phantom replica "
                f"reservations); guard with `with {base}.lock:` or go "
                f"through the registry accessors",
                node,
            ), node
