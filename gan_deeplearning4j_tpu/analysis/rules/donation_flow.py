"""JG010 — donation tracking through ``functools.partial`` / indirection.

JG006 proves use-after-donate for donating callables *discovered in the
same module*. The hazards the ROADMAP queued next hide the donation behind
one more hop, where a reviewer reading the call site sees nothing about
donation at all:

1. **partial over a donator** — ``p = functools.partial(step, cfg)`` where
   ``step = jax.jit(fn, donate_argnums=(0,))``:

   - if a donated position is among the BOUND arguments, the partial
     donates the same captured buffer on EVERY call — the second call
     passes an already-donated array (flagged at the partial construction,
     unconditionally: there is no safe way to call it twice);
   - otherwise the donated positions SHIFT by the number of bound
     positional arguments at the partial's call sites — ``p``'s argument
     ``i`` is ``step``'s ``i + len(bound)`` — and use-after-donate must be
     checked against the shifted positions.

2. **imported donators** — ``from harness.steps import step`` then
   ``step(state, ...); state.mean()``: the donation lives in another file.
   Phase 1 records module-level donators (including ``step = make_step()``
   builder results) per module; this rule checks call sites in every
   importing module against them.

Same call-site semantics as JG006 (the shared
:func:`~.donation.scan_use_after_donate` scanner); only discovery differs,
so a defect is reported under exactly one of the two codes.
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common
from gan_deeplearning4j_tpu.analysis.rules import donation as _donation


class DonationFlow:
    code = "JG010"
    name = "donation-flow"
    summary = ("donated buffer misused through functools.partial or an "
               "imported donating callable")

    def check(self, mod):
        local = _donation.DonationSafety()._collect_donators(mod)
        flow: dict = {}
        info = None

        # (a) module-level donators imported from other indexed modules
        if mod.project is not None:
            info = mod.project.by_path.get(mod.path)
            for local_name in (info.imports if info else {}):
                nums = mod.project.imported_donator(mod, local_name)
                if nums and local_name not in local:
                    flow[local_name] = nums

        # (b) name = builder() where the builder lives in another module
        if mod.project is not None and info is not None:
            for stmt in mod.tree.body:
                if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Call)):
                    continue
                name = stmt.targets[0].id
                if name in local or name in flow:
                    continue
                summary = mod.project.resolve_function(mod, stmt.value.func)
                if (summary is not None and summary.module != info.name
                        and summary.returns_donation):
                    flow[name] = summary.returns_donation

        # (c) partials over any known donator. Partial aliases are SCOPED to
        # the function (or module body) that constructs them: registering
        # them module-wide would flag an unrelated local that merely shares
        # the variable name in another function.
        for f, node, name, shifted in self._partials(mod.tree, mod,
                                                     {**local, **flow}):
            if f is not None:
                yield f, node
            else:
                flow[name] = shifted  # module-level alias: visible everywhere
        for scope in _common.iter_scopes(mod.tree):
            if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            scope_flow = dict(flow)
            for f, node, name, shifted in self._partials(
                    scope, mod, {**local, **scope_flow}):
                if f is not None:
                    yield f, node
                else:
                    scope_flow[name] = shifted
            if scope_flow:
                yield from _donation.scan_use_after_donate(
                    scope, scope_flow, mod, self.code
                )

    def _partials(self, root, mod, known):
        """Partial-over-donator assignments among ``root``'s OWN statements
        (nested function bodies excluded — they are their own scopes).
        Yields ``(finding, node, None, None)`` for a bound-donated-position
        partial, ``(None, None, name, shifted_argnums)`` for a clean alias
        whose donated positions shifted by the bound-argument count."""
        for stmt in _common.walk_excluding_defs(root):
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)
                    and mod.resolve(stmt.value.func) == "functools.partial"
                    and stmt.value.args):
                continue
            target_key = _donation._arg_key(stmt.value.args[0])
            nums = known.get(target_key)
            if not nums:
                continue
            bound = len(stmt.value.args) - 1
            donated_bound = [i for i in nums if i < bound]
            if donated_bound:
                f = mod.finding(
                    self.code,
                    f"functools.partial binds `{target_key}`'s argument "
                    f"at donated position{'s' if len(donated_bound) > 1 else ''} "
                    f"{tuple(donated_bound)} — the captured buffer is "
                    f"donated on EVERY call, so any second call passes "
                    f"an already-donated array; bind non-donated "
                    f"arguments only, or drop the donation",
                    stmt.value,
                )
                yield f, stmt.value, None, None
            else:
                # positions shift: partial arg i is target arg i+bound
                yield None, None, stmt.targets[0].id, tuple(
                    i - bound for i in nums if i >= bound
                )
