"""JG032 — double-buffer consumed while its overlapped fill is in flight.

The streaming input pipeline (zoo/streaming.py) overlaps the next block's
fill with consumption of the current block: a worker is handed the buffer
(``executor.submit(self._fill, back)``) while the consumer slices batches
out of the front buffer. The discipline that makes this safe is the FENCE:
the future's ``result()`` (or a ``join()``/``wait()``, or the tuple swap
that retires the front buffer) must happen before anything READS the
buffer the fill was issued against. Dropping the fence is the classic
double-buffering bug — the consumer reads rows the worker is still
writing, producing silently torn batches that train fine and converge
wrong. It is also invisible to tests at small scale, where the fill wins
the race by accident.

Queued in ROADMAP since PR 10 introduced the ``DevicePrefetchIterator``
``transform=`` seam; the streaming pipeline makes the shape load-bearing.

The rule is scope-local and flow-free, in the house style:

1. an *overlapped fill* is ``<pool>.submit(f, buf, ...)`` or
   ``Thread(target=f, args=(buf, ...))`` where ``f``'s terminal
   identifier contains ``fill``, ``refill``, or ``prefetch`` — the repo's
   naming seam for background buffer writers;
2. its *buffers* are the Name/Attribute arguments handed to ``f``
   (matched by dotted path, so ``self._back`` is tracked);
3. a *consumption read* is a later subscript of the buffer
   (``back[i]``, ``back[lo:hi]``) or iteration over it
   (``for row in back:``) in the same scope — a bare mention (len(),
   passing it along) is not consumption and does not fire;
4. a *fence* clears the hazard: any ``.result()``/``.join()``/``.wait()``
   call, or a swap assignment whose targets include the buffer
   (``front, back = back, front`` — the read-after names then refer to
   retired storage), between the issue and the read.

True negatives: fence-then-read (zoo/streaming.py's ``_promote``), reads
that precede the issue (consume-then-refill, the other legal ordering),
non-buffer arguments (``submit(self._fill, start_index)`` where the index
is never subscripted), and worker callees without the naming seam.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from gan_deeplearning4j_tpu.analysis import _common

_FILL_TOKENS = ("fill", "refill", "prefetch")
_FENCE_ATTRS = ("result", "join", "wait")


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """``buf`` / ``self._back`` as a stable dotted path (None for anything
    more dynamic — calls, subscripts — which this flow-free rule skips)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def _is_fill_callee(node: ast.AST) -> bool:
    name = _terminal(node)
    return name is not None and any(t in name.lower() for t in _FILL_TOKENS)


class DoubleBufferMisuse:
    code = "JG032"
    name = "double-buffer-misuse"
    summary = ("buffer read after its overlapped fill was issued, with no "
               "fence or swap in between")

    # -- issue sites -------------------------------------------------------
    def _fill_buffers(self, call: ast.Call) -> Optional[List[ast.AST]]:
        """The buffer arguments of an overlapped-fill call, or None when
        this call is not one."""
        # <pool>.submit(fill_fn, buf, ...)
        if (_terminal(call.func) == "submit" and call.args
                and _is_fill_callee(call.args[0])):
            return list(call.args[1:])
        # Thread(target=fill_fn, args=(buf, ...))
        if _terminal(call.func) == "Thread":
            target = None
            args: List[ast.AST] = []
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
                elif kw.arg == "args" and isinstance(kw.value, ast.Tuple):
                    args = list(kw.value.elts)
            if target is not None and _is_fill_callee(target):
                return args
        return None

    # -- the check ---------------------------------------------------------
    def check(self, mod):
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None)
            if not body:
                continue
            nodes = sorted(
                _common.walk_excluding_defs(body),
                key=lambda n: getattr(n, "lineno", 0),
            )
            # issued[buffer dotted path] = issue line
            issued: Dict[str, int] = {}
            flagged: set = set()
            for n in nodes:
                line = getattr(n, "lineno", 0)
                # fences first: a .result()/.join()/.wait() clears every
                # outstanding issue (flow-free: any fence on the path
                # counts), a swap assignment retires the swapped buffers
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _FENCE_ATTRS:
                    issued.clear()
                    continue
                if isinstance(n, ast.Assign):
                    for tgt in n.targets:
                        elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                        for e in elts:
                            path = _dotted(e)
                            if path is not None:
                                issued.pop(path, None)
                if isinstance(n, ast.Call):
                    buffers = self._fill_buffers(n)
                    if buffers:
                        for b in buffers:
                            path = _dotted(b)
                            if path is not None:
                                issued.setdefault(path, line)
                        continue
                if not issued:
                    continue
                # consumption reads of an issued buffer
                read: Optional[Tuple[str, ast.AST]] = None
                if isinstance(n, ast.Subscript):
                    path = _dotted(n.value)
                    if path in issued and line > issued[path]:
                        read = (path, n)
                elif isinstance(n, (ast.For, ast.AsyncFor)):
                    path = _dotted(n.iter)
                    if path in issued and line > issued[path]:
                        read = (path, n)
                if read is None or read[0] in flagged:
                    continue
                path, node = read
                flagged.add(path)
                yield mod.finding(
                    self.code,
                    f"`{path}` is read here, but its overlapped fill was "
                    f"issued on line {issued[path]} and nothing fences the "
                    f"worker in between — the consumer can observe a "
                    f"half-written buffer (torn batches that train wrong "
                    f"silently); call the future's .result() (or "
                    f".join()/.wait(), or swap the buffers) before reading",
                    node,
                ), node
