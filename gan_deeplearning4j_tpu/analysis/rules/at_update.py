"""JG007 — discarded ``.at[...].set()`` result.

JAX arrays are immutable: ``x.at[i].set(v)`` (and ``.add``, ``.multiply``,
``.min``, ``.max``, ``.apply``, ...) returns a NEW array and leaves ``x``
untouched. Writing it as a bare statement — the reflex of every
numpy/PyTorch in-place habit — is a silent no-op: the program traces, jits,
and runs, producing numbers computed from the un-updated array. This is the
ROADMAP-queued hazard class with the worst detectability-to-cost ratio:
nothing crashes, the update just never happens.

The rule flags any expression STATEMENT whose value is an indexed-update
call. Fixable (``--fix``): when the updated object is a plain name or
dotted attribute, the mechanical rewrite ``x = x.at[i].set(v)`` restores
the intended semantics; exotic bases (calls, subscripts) are reported but
left to a human.

True negative: any use of the result — assignment, return, argument,
carry — and ``.at[...].get()``, whose result being discarded is dead code
but not a wrong-answer hazard (still flagged: a discarded ``.get()`` is
either a typo for a fence or leftover debugging).
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common

#: the indexed-update methods of jax's ``.at`` property
AT_METHODS = {
    "set", "add", "subtract", "sub", "multiply", "mul", "divide", "div",
    "power", "min", "max", "apply", "get",
}


def at_update_call(node: ast.AST):
    """The ``(base_expr, method)`` of an ``<base>.at[...].<method>(...)``
    call, else None. ``base_expr`` is the AST of ``<base>``."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in AT_METHODS):
        return None
    sub = node.func.value
    if not (isinstance(sub, ast.Subscript)
            and isinstance(sub.value, ast.Attribute)
            and sub.value.attr == "at"):
        return None
    return sub.value.value, node.func.attr


def fixable_base_text(base: ast.AST):
    """Source text to rebind when the base is mechanically rebindable —
    a bare name or a dotted attribute chain (``self.params``); anything
    with calls/subscripts in it is not a safe mechanical target."""
    node = base
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return ast.unparse(base)
    return None


class DiscardedAtUpdate:
    code = "JG007"
    name = "discarded-at-update"
    summary = ".at[...].set() result discarded — functional update is a no-op"

    def check(self, mod):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Expr):
                continue
            hit = at_update_call(node.value)
            if hit is None:
                continue
            base, method = hit
            base_text = fixable_base_text(base)
            target = base_text or ast.unparse(base)
            f = mod.finding(
                self.code,
                f"`.at[...].{method}()` returns a new array and this "
                f"statement discards it — `{target}` is unchanged (JAX "
                f"arrays are immutable); rebind: "
                f"`{target} = {ast.unparse(node.value)[:60]}`",
                node,
            )
            yield f, node
