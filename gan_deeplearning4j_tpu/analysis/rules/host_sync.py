"""JG005 — implicit host sync / trace-time leak inside traced code.

Inside a jitted function or a ``lax.scan``/``while_loop``/``cond`` body,
values are tracers. Host-crossing operations there are either a
``ConcretizationTypeError`` (``float()``, ``int()``, ``.item()``,
``np.asarray`` on a traced value) or — worse — silently wrong: ``print``
executes ONCE at trace time showing a tracer repr, then never again, which
is exactly how debugging leftovers masquerade as per-step logging. On the
tunneled axon platform an accidental device->host read also serializes the
pipeline the whole bench architecture exists to keep full.

Traced bodies are found syntactically: defs decorated with ``jax.jit`` /
``jax.pmap`` (directly or via ``functools.partial``), functions or lambdas
passed to ``jax.jit``/``jax.pmap``/``jax.grad``/``jax.vmap`` or to
``jax.lax`` control-flow combinators (``scan``, ``while_loop``,
``fori_loop``, ``cond``, ``switch``, ``map``, ``associative_scan``), plus
every def nested inside one. Shape arithmetic is exempt: ``int(x.shape[0])``
and friends are static under tracing and idiomatic.
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common

_TRACING_WRAPPERS = _common.TRACING_WRAPPERS
_HOST_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
    "numpy.save", "numpy.savez", "jax.device_get",
}
_HOST_METHODS = {"item", "tolist"}
_CASTS = {"float", "int", "bool", "complex"}
# attribute/function sniffs that mark an expression as static shape math
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize"}


def _is_tracing_wrapper(node: ast.AST, mod) -> bool:
    resolved = mod.resolve(node)
    if resolved in _TRACING_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        r = mod.resolve(node.func)
        if r in _TRACING_WRAPPERS:
            return True
        if r == "functools.partial" and node.args:
            return mod.resolve(node.args[0]) in _TRACING_WRAPPERS
    return False


def _static_shape_expr(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return True
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "len"):
            return True
    return False


class HostSyncInTracedCode:
    code = "JG005"
    name = "host-sync-in-traced-code"
    summary = ("host-crossing call (print/float/.item/np.asarray) inside a "
               "jit or lax control-flow body")

    def check(self, mod):
        traced = self._traced_functions(mod)
        reported = set()
        for fn in traced:
            body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
            for stmt in body:
                for n in ast.walk(stmt):
                    if id(n) in reported or not isinstance(n, ast.Call):
                        continue
                    msg = self._host_call_message(n, mod)
                    if msg:
                        reported.add(id(n))
                        yield mod.finding(self.code, msg, n), n

    # -- traced-function discovery -----------------------------------------
    def _traced_functions(self, mod):
        traced = []
        # defs by name per enclosing scope, to resolve f in jax.jit(f)
        defs_by_name = {}
        for n in ast.walk(mod.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(n.name, []).append(n)
        for n in ast.walk(mod.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in n.decorator_list:
                    if _is_tracing_wrapper(dec, mod):
                        traced.append(n)
                        break
            elif isinstance(n, ast.Call) and _is_tracing_wrapper(n, mod):
                for arg in n.args:
                    if isinstance(arg, ast.Lambda):
                        traced.append(arg)
                    elif isinstance(arg, ast.Name):
                        for d in defs_by_name.get(arg.id, []):
                            traced.append(d)
        return traced

    # -- host-call classification ------------------------------------------
    def _host_call_message(self, call: ast.Call, mod):
        resolved = mod.resolve(call.func)
        if resolved in _HOST_CALLS:
            return (f"`{resolved.replace('numpy', 'np')}` inside traced code "
                    f"forces a device->host transfer (ConcretizationTypeError "
                    f"under jit) — keep the value on device or move this out "
                    f"of the traced body")
        if resolved == "print":
            return ("print inside traced code executes once at TRACE time "
                    "with a tracer repr, never per step — use "
                    "jax.debug.print or return the value")
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in _HOST_METHODS):
            return (f"`.{call.func.attr}()` inside traced code forces a "
                    f"host sync — return the array and read it outside the "
                    f"traced body")
        if (isinstance(call.func, ast.Name) and call.func.id in _CASTS
                and call.func.id not in mod.imports and len(call.args) == 1
                and not isinstance(call.args[0], ast.Constant)
                and not _static_shape_expr(call.args[0])):
            return (f"`{call.func.id}()` on a traced value raises "
                    f"ConcretizationTypeError under jit (and is a host sync "
                    f"outside) — use jnp casts or shape-static arithmetic")
        return None
