"""JG024 — shared mutable attribute escapes its majority lock.

Generalizes JG016 beyond ``swap*`` classes to every threaded plane the
fleet now runs: the router's health loop mutates member tables the request
threads read, the autoscaler tick resizes what the manager loop walks, the
reload controller rebinds candidate state the /healthz handler snapshots,
the alert evaluator appends to event lists the drill reader drains. The
drills catch these races only probabilistically; this rule catches the
*inconsistency* statically.

The model (from the phase-1 concurrency index, :mod:`..concurrency`): a
class that spawns threads (``Thread(target=self._loop)``, ``Timer``,
``run`` of a ``Thread`` subclass) has ≥2 concurrent contexts — each
spawned entry point's same-class call closure, plus ``<caller>`` for the
public API. An instance attribute is *shared mutable state* when it is
mutated outside ``__init__`` (rebound, aug-assigned, subscript-stored, or
used through a mutator method like ``.append``) and touched from ≥2
contexts. When most of its accesses sit under one lock (≥2 guarded
accesses under lock L, strictly more than the accesses escaping L) but at
least one access escapes unguarded, each escape is flagged: the lock
discipline exists, and the escape is where another thread observes a torn
rebind or lost update.

Not flagged (true negatives): ``__init__`` (single-threaded construction,
as in JG016); never-locked attributes (no discipline to escape — Events
and atomic flags live here by design); attributes only read outside
``__init__``; classes that spawn no threads; accesses in ``*_locked``
methods and in private helpers whose every in-class call site holds the
lock (the caller-holds-the-lock convention); ``BaseHTTPRequestHandler``
subclasses (instances are per-request, so ``self`` attrs are not shared).

Known false negatives (static visibility only): module-global state shared
by module-level thread targets; attributes reached through non-``self``
bases; 50/50 guarded/unguarded splits (no majority — no discipline to
enforce); ``.acquire()``/``.release()`` pairs outside ``with``.
"""

from __future__ import annotations

from collections import Counter, defaultdict


class UnguardedSharedMutableState:
    code = "JG024"
    name = "unguarded-shared-mutable-state"
    summary = ("attribute shared across thread contexts escapes the lock "
               "that guards its other accesses")
    skip_tests = True

    def check(self, mod):
        if mod.project is None:
            return
        for cc in mod.project.concurrency.classes(mod.path):
            if not cc.instance_shared or not cc.entry_points:
                continue
            spawned = [e for e, kind in cc.entry_points.items()
                       if kind != "http-handler"]
            if not spawned:
                continue
            contexts = cc.thread_contexts()
            if len(contexts) < 2:
                continue
            yield from self._scan_class(mod, cc, contexts)

    def _scan_class(self, mod, cc, contexts):
        by_attr = defaultdict(list)
        for name, mc in cc.methods.items():
            if name == "__init__" or name.endswith("_locked"):
                continue
            for a in mc.accesses:
                if a.attr in cc.lock_attrs or a.attr in cc.lock_aliases:
                    continue
                by_attr[a.attr].append(
                    (a, a.held | mc.caller_held))
        for attr in sorted(by_attr):
            accesses = by_attr[attr]
            if not any(a.is_mutating for a, _ in accesses):
                continue  # read-only outside __init__: config, not state
            touched = {a.method for a, _ in accesses}
            hit = sum(1 for _, members in contexts if touched & members)
            if hit < 2:
                continue  # one thread owns it
            guard_votes = Counter()
            for _, held in accesses:
                for lock in held:
                    guard_votes[lock] += 1
            if not guard_votes:
                continue  # never locked anywhere: no discipline to escape
            # deterministic majority pick: most votes, ties by name
            lock, votes = sorted(
                guard_votes.items(), key=lambda kv: (-kv[1], kv[0]))[0]
            escapes = [(a, held) for a, held in accesses if lock not in held]
            if votes < 2 or votes <= len(escapes):
                continue  # no majority: not a discipline, a coincidence
            entries = ", ".join(
                f"`{e}`" for e in sorted(cc.entry_points))
            for a, _ in escapes:
                verb = ("mutates" if a.is_store or a.is_mutating
                        else "reads")
                yield mod.finding(
                    self.code,
                    f"`{a.method}` {verb} `self.{attr}` without holding "
                    f"`{lock.rpartition('.')[2]}` — `{cc.name}` runs "
                    f"threads ({entries}) and guards this attribute's "
                    f"other {votes} access(es) with that lock, so this "
                    f"escape can observe a torn rebind or lose an update; "
                    f"guard it or snapshot the attribute to a local under "
                    f"the lock",
                    a.node,
                ), a.node
