"""JG031 — hard-coded bucket ladder at a manifest-carrying load seam.

The traffic-shaped ladder contract (docs/SERVING.md, serving/ladder.py):
a published bundle carries its learned bucket ladder in ``serving.json``,
and every loader that takes a bundle directory — ``from_bundle``, the mux
registry's ``build_engine``, ``measure_bundle_cost`` — resolves that
manifest ladder when ``buckets`` is omitted. Passing a literal ladder at
one of these seams silently overrides what the bundle learned from live
traffic: the engine compiles the author's guess, the cost block prices a
ladder the variant will never serve, and the padding-waste win the
reload plane accumulated across generations is thrown away at load time.
(The pre-learning default lives in ONE place — ``DEFAULT_BUCKETS`` — so
a literal at a load seam is never the right spelling of "the default".)

The rule flags a call whose callee name (attribute or bare) is one of
the bundle-loading seams AND whose ``buckets`` keyword is a list/tuple
literal of integer constants.

True negatives: ``buckets=None`` (explicit manifest resolution);
``buckets=args.buckets`` or any other non-literal expression (operator
override, a solved ladder, ``DEFAULT_BUCKETS``); no ``buckets`` kwarg at
all; ``from_checkpoints(buckets=[...])`` — raw checkpoints carry no
manifest, a literal is the only way to say anything. Test modules are
exempt (``skip_tests``): fixtures legitimately pin tiny ladders to make
compile counts deterministic.
"""

from __future__ import annotations

import ast

#: callee names whose ``buckets=`` kwarg shadows a bundle manifest ladder
_BUNDLE_SEAMS = ("from_bundle", "measure_bundle_cost", "build_engine")


def _callee_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _literal_int_ladder(node) -> bool:
    return (isinstance(node, (ast.List, ast.Tuple))
            and bool(node.elts)
            and all(isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                    and not isinstance(e.value, bool)
                    for e in node.elts))


class HardcodedLadderLiteral:
    code = "JG031"
    name = "hardcoded-ladder-literal"
    summary = ("literal bucket ladder passed at a bundle-loading seam — "
               "overrides the learned manifest ladder the bundle carries")
    skip_tests = True  # tests pin tiny ladders for deterministic compiles

    def check(self, mod):
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.Call):
                continue
            seam = _callee_name(n.func)
            if seam not in _BUNDLE_SEAMS:
                continue
            for kw in n.keywords:
                if kw.arg != "buckets":
                    continue
                if not _literal_int_ladder(kw.value):
                    continue
                f = mod.finding(
                    self.code,
                    f"{seam}() called with a literal bucket ladder — this "
                    f"seam resolves the bundle's LEARNED manifest ladder "
                    f"when buckets is omitted (serving/ladder.py), so a "
                    f"hard-coded list silently discards the traffic-shaped "
                    f"buckets the reload plane solved and compiles the "
                    f"author's guess instead; pass buckets=None (or a "
                    f"computed ladder / DEFAULT_BUCKETS) and let the "
                    f"manifest win",
                    kw.value,
                )
                yield f, n
