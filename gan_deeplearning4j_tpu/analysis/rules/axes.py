"""JG011 — statically-visible ``pmap``/``vmap`` axis mismatch.

``jax.vmap``/``jax.pmap`` fail at TRACE time when ``in_axes`` does not
match the mapped function's arity, or when the mapped axes of the actual
arguments disagree in size — but "trace time" on this repo's target
platform is minutes into a run, after the XLA compile queue, on an
exclusively-held chip. Whole-program compilers reject these programs before
they touch hardware (PAPERS.md: TensorFlow's static dataflow checking,
Julia-to-TPU's shape inference); this rule recovers the statically-visible
subset at lint time:

1. **in_axes arity** — a literal ``in_axes`` tuple whose length differs
   from the mapped callable's positional arity. The callable is resolved
   through the project index, so ``jax.vmap(loss_fn, in_axes=(0, 0, None))``
   is checked even when ``loss_fn`` lives in another module. Functions with
   ``*args`` are skipped (arity unknowable), as are default-bearing arities
   that could legitimately match.
2. **call-site arity** — ``jax.vmap(f, in_axes=(0, 0))(x)``: literal tuple
   length vs the immediate call's positional argument count.
3. **axis sizes** — arguments that are names bound in the same scope to
   literal-shaped constructors (``jnp.zeros((4, 3))``,
   ``jax.random.normal(k, (8, 2))``, ...) must agree on the mapped axis
   size. ``in_axes=None`` entries are skipped; integer entries pick the
   axis they name.

All checks fire only on statically-certain evidence — an unresolvable
callable or a shape-unknown argument is silence, not a guess.
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common

_MAP_WRAPPERS = {"jax.vmap", "jax.pmap"}

#: constructors whose FIRST argument is a literal shape
_SHAPE_FIRST = {
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.empty", "jax.numpy.full",
    "numpy.zeros", "numpy.ones", "numpy.empty", "numpy.full",
}
#: jax.random samplers whose SECOND argument is a literal shape
_SHAPE_SECOND = {
    "jax.random.normal", "jax.random.uniform", "jax.random.bernoulli",
    "jax.random.randint", "jax.random.truncated_normal",
}


def _literal_axes(node):
    """in_axes as a list of int/None, or None when not a literal."""
    if isinstance(node, ast.Constant) and (
            node.value is None or isinstance(node.value, int)):
        return node.value  # scalar broadcast spec — applies to every arg
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and (
                    elt.value is None or isinstance(elt.value, int)):
                out.append(elt.value)
            else:
                return None
        return out
    return None


def _in_axes_node(map_call: ast.Call):
    if len(map_call.args) > 1:
        return map_call.args[1]
    for kw in map_call.keywords:
        if kw.arg == "in_axes":
            return kw.value
    return None


def _shape_bindings(scope, mod) -> dict:
    """name -> literal shape tuple, from constructor calls in ``scope``."""
    shapes: dict = {}
    for stmt in ast.walk(scope):
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)):
            continue
        call = stmt.value
        resolved = mod.resolve(call.func)
        shape_node = None
        if resolved in _SHAPE_FIRST and call.args:
            shape_node = call.args[0]
        elif resolved in _SHAPE_SECOND and len(call.args) > 1:
            shape_node = call.args[1]
        else:
            for kw in call.keywords:
                if kw.arg == "shape" and resolved and (
                        resolved in _SHAPE_FIRST or resolved in _SHAPE_SECOND):
                    shape_node = kw.value
        if shape_node is None:
            continue
        shape = _common.literal_int_tuple(shape_node)
        if shape is not None:
            shapes[stmt.targets[0].id] = shape
    return shapes


class AxisSizeMismatch:
    code = "JG011"
    name = "axis-size-mismatch"
    summary = "pmap/vmap in_axes arity or mapped axis sizes provably mismatch"

    def check(self, mod):
        for scope in _common.iter_scopes(mod.tree):
            if getattr(scope, "body", None) is None:
                continue
            shapes = _shape_bindings(scope, mod)
            # mapped-callable bindings in this scope: g = jax.vmap(f, ...)
            mapped_by_name: dict = {}
            for stmt in ast.walk(scope):
                if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Call)
                        and mod.resolve(stmt.value.func) in _MAP_WRAPPERS):
                    mapped_by_name[stmt.targets[0].id] = stmt.value
            for n in ast.walk(scope):
                if not isinstance(n, ast.Call):
                    continue
                # direct: jax.vmap(f, ...)(args)
                if (isinstance(n.func, ast.Call)
                        and mod.resolve(n.func.func) in _MAP_WRAPPERS):
                    yield from self._check_map(n.func, n, mod, shapes)
                # bare construction without immediate call: arity check only
                elif mod.resolve(n.func) in _MAP_WRAPPERS:
                    yield from self._check_map(n, None, mod, shapes)
                # through a binding: g = jax.vmap(f, ...); g(args)
                elif (isinstance(n.func, ast.Name)
                        and n.func.id in mapped_by_name):
                    yield from self._check_map(
                        mapped_by_name[n.func.id], n, mod, shapes)

    def _check_map(self, map_call, outer_call, mod, shapes):
        axes = _literal_axes(_in_axes_node(map_call)) \
            if _in_axes_node(map_call) is not None else 0
        fn_name = (ast.unparse(map_call.args[0])
                   if map_call.args else "<unknown>")
        wrapper = mod.resolve(map_call.func)

        # 1. in_axes tuple vs the mapped callable's arity (index-resolved)
        if isinstance(axes, list) and map_call.args and mod.project is not None:
            summary = mod.project.resolve_function(mod, map_call.args[0])
            if (summary is not None and summary.node is not None
                    and not summary.node.args.vararg
                    and not (summary.min_arity <= len(axes)
                             <= len(summary.params))):
                f = mod.finding(
                    self.code,
                    f"in_axes has {len(axes)} entries but `{fn_name}` "
                    f"({summary.fq}) takes "
                    f"{summary.min_arity}"
                    + (f"-{len(summary.params)}"
                       if len(summary.params) != summary.min_arity else "")
                    + " positional arguments — "
                    f"{wrapper} raises at trace time; align in_axes with "
                    f"the signature",
                    map_call,
                )
                yield f, map_call
                return
        if outer_call is None:
            return
        n_args = len(outer_call.args)
        if any(isinstance(a, ast.Starred) for a in outer_call.args):
            return
        # 2. in_axes tuple vs the immediate call-site arity
        if isinstance(axes, list) and n_args and len(axes) != n_args:
            f = mod.finding(
                self.code,
                f"in_axes has {len(axes)} entries but this call passes "
                f"{n_args} positional argument{'s' if n_args != 1 else ''} "
                f"— {wrapper} raises at trace time",
                outer_call,
            )
            yield f, outer_call
            return
        # 3. mapped axis sizes from literal-shaped bindings
        sized = []  # (arg_name, axis, size)
        for i, arg in enumerate(outer_call.args):
            axis = axes[i] if isinstance(axes, list) and i < len(axes) else axes
            if axis is None or not isinstance(axis, int):
                continue
            if not isinstance(arg, ast.Name) or arg.id not in shapes:
                continue
            shape = shapes[arg.id]
            ax = axis if axis >= 0 else len(shape) + axis
            if 0 <= ax < len(shape):
                sized.append((arg.id, axis, shape[ax]))
        if len({s for _, _, s in sized}) > 1:
            detail = ", ".join(
                f"`{name}` axis {axis} has size {size}"
                for name, axis, size in sized
            )
            f = mod.finding(
                self.code,
                f"mapped axis sizes disagree at this {wrapper} call: "
                f"{detail} — every mapped argument must share the mapped "
                f"axis size; fix the shapes or the in_axes spec",
                outer_call,
            )
            yield f, outer_call
