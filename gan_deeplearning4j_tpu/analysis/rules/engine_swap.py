"""JG016 — swappable engine attribute touched outside the lock/swap seam.

The reload plane (deploy/) hot-swaps the serving engine under the
micro-batcher's lock: ``swap_engine`` rebinds ``self._engine`` while
worker, completer, and HTTP threads are all running. The seam only works
if EVERY access to the swappable attribute goes through that lock — an
unguarded ``self._engine.dispatch(...)`` can pair a flush cut from the old
engine with a dispatch on the new one, which finalizes foreign staging
buffers and releases phantom replica reservations (the reload-plane
thread-safety hazard the ROADMAP queued this rule for). The correct idioms
are a lock-guarded accessor, or snapshotting the attribute to a local
under the lock and using the local.

The rule: in any class with a ``swap*`` method, an attribute that method
rebinds (plain assignment — augmented counters like ``self._swaps += 1``
are not swap targets) is *swappable*; every load or store of it in any
method other than ``__init__`` must sit inside a ``with`` block whose
context expression is a lock-ish ``self`` attribute (name containing
"lock", or a condition variable: ``_cv``/``cond``/...). The swap method
itself is held to the same bar — a swap seam that rebinds without the
lock is the worst offender, not an exemption.

True negatives: reads under ``with self._lock:`` (or the condition
variable that wraps it), locals snapshotted under the lock, ``__init__``
(construction is single-threaded by contract), and classes with no swap
method at all.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

#: with-context attribute names that count as holding the swap lock
_LOCK_NAMES = {"_cv", "cv", "_cond", "cond", "_condition", "condition",
               "_mutex", "mutex"}


def _is_lockish(expr: ast.AST) -> bool:
    """``self.<lock-ish>`` (optionally ``self.<lock>.acquire_…()`` style
    calls are NOT with-contexts here — only the plain attribute)."""
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        name = expr.attr
        return "lock" in name.lower() or name in _LOCK_NAMES
    return False


def _self_attr(node: ast.AST):
    """The attribute name of a ``self.<attr>`` node, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _assign_targets(node: ast.AST) -> Iterable[ast.AST]:
    """Flatten plain-assignment targets through tuple/list unpacking
    (``old, self._engine = self._engine, new``)."""
    if isinstance(node, ast.Assign):
        stack = list(node.targets)
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            else:
                yield t


class SwapSeamUnguardedAccess:
    code = "JG016"
    name = "engine-swap-unguarded-access"
    summary = ("swappable engine attribute accessed outside the batcher's "
               "lock/swap seam")
    skip_tests = True

    def check(self, mod):
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            swap_methods = [m for m in methods
                            if m.name.lstrip("_").startswith("swap")]
            swappable: Set[str] = set()
            for m in swap_methods:
                for node in ast.walk(m):
                    for target in _assign_targets(node):
                        attr = _self_attr(target)
                        if attr is not None:
                            swappable.add(attr)
            if not swappable:
                continue
            for m in methods:
                if m.name == "__init__":
                    continue  # construction is single-threaded by contract
                yield from self._scan(mod, cls, m, swappable)

    def _scan(self, mod, cls, method, swappable: Set[str]):
        hits = []

        def visit(node: ast.AST, guarded: bool) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = guarded or any(
                    _is_lockish(item.context_expr) for item in node.items)
                for item in node.items:
                    visit(item, guarded)
                for child in node.body:
                    visit(child, inner)
                return
            attr = _self_attr(node)
            if attr in swappable and not guarded:
                hits.append((node, attr,
                             isinstance(getattr(node, "ctx", None),
                                        ast.Store)))
            for child in ast.iter_child_nodes(node):
                visit(child, guarded)

        for stmt in method.body:
            visit(stmt, False)
        for node, attr, is_store in hits:
            verb = "rebinds" if is_store else "reads"
            yield mod.finding(
                self.code,
                f"`{method.name}` {verb} swappable attribute `self.{attr}` "
                f"outside the lock — `{cls.name}` hot-swaps it in its "
                f"swap method, so another thread can observe a"
                f"{' torn rebind' if is_store else ' mid-swap value'}; "
                f"guard with `with self._lock:` or snapshot it to a local "
                f"under the lock",
                node,
            ), node
