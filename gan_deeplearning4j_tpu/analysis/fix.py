"""jaxlint ``--fix``: mechanical, idempotent source rewrites.

The autofix contract (documented in docs/STATIC_ANALYSIS.md):

- Only rewrites whose semantics are fully determined by the finding are
  applied — no judgement calls, no formatting beyond the touched lines:

  * **JG003** ``assert test[, msg]`` → ``if not (test): raise
    AssertionError(msg)`` — the explicit form survives ``python -O``;
  * **JG007** a discarded ``x.at[i].set(v)`` statement → ``x = x.at[i]
    .set(v)`` — only when the updated base is a plain name or dotted
    attribute (anything else is reported but left to a human);
  * **suppression insertion** (``--fix-suppress``, requires a
    ``--justification``): appends ``# jaxlint: disable=<code> -- <why>``
    to each remaining active finding's line. The justification is
    mandatory for the same reason baseline entries require one: "suppress
    it" must never silently become "ignore it".

- **Idempotency**: a fixed line no longer matches its rule, and a
  suppressed finding is categorized as suppressed, so running any fix mode
  twice is a no-op (tested in tests/test_analysis.py).
- Fixes apply to ACTIVE findings only — suppressed and baselined findings
  are someone's recorded decision and are left alone.
- Statements that do not start their line (``if x: assert y``) are skipped:
  a rewrite there would need to restructure the compound statement, which
  is not mechanical.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional

from gan_deeplearning4j_tpu.analysis import engine
from gan_deeplearning4j_tpu.analysis.rules import at_update as _at_update

#: rules --fix can rewrite (suppression insertion covers every code)
FIXABLE_CODES = ("JG003", "JG007")

_DISABLE_RE = re.compile(r"(#\s*jaxlint:\s*disable=)([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass
class FixResult:
    rewritten: int          # findings fixed by rewriting code
    suppressed: int         # findings fixed by inserting suppressions
    skipped: List[str]      # findings seen but not mechanically fixable
    files: List[str]        # files actually modified


def _starts_line(lines: List[str], lineno: int, col: int) -> bool:
    line = lines[lineno - 1] if 1 <= lineno <= len(lines) else ""
    return line[:col].strip() == ""


def _fix_assert(node: ast.Assert, lines: List[str]) -> Optional[List[str]]:
    """Replacement lines for a bare assert, or None when not mechanical."""
    if not _starts_line(lines, node.lineno, node.col_offset):
        return None
    indent = " " * node.col_offset
    test = ast.unparse(node.test)
    msg = ast.unparse(node.msg) if node.msg is not None else ""
    return [
        f"{indent}if not ({test}):",
        f"{indent}    raise AssertionError({msg})",
    ]


def _fix_at_update(node: ast.Expr, lines: List[str]) -> Optional[List[str]]:
    """Prepend ``base = `` to a discarded indexed-update statement."""
    hit = _at_update.at_update_call(node.value)
    if hit is None:
        return None
    base, _ = hit
    base_text = _at_update.fixable_base_text(base)
    if base_text is None or not _starts_line(lines, node.lineno,
                                             node.col_offset):
        return None
    first = lines[node.lineno - 1]
    patched = (first[: node.col_offset] + f"{base_text} = "
               + first[node.col_offset:])
    out = [patched]
    out.extend(lines[node.lineno: (node.end_lineno or node.lineno)])
    return out


def _node_at(tree: ast.AST, kind, lineno: int):
    for n in ast.walk(tree):
        if isinstance(n, kind) and getattr(n, "lineno", None) == lineno:
            return n
    return None


def _apply_rewrites(path: str, findings: List[engine.Finding]) -> tuple:
    """Rewrite one file bottom-up. Returns (n_fixed, skipped_renders)."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    lines = text.splitlines()
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return 0, [f.render() for f in findings]
    fixed, skipped = 0, []
    for f in sorted(findings, key=lambda f: -f.line):
        if f.code == "JG003":
            node = _node_at(tree, ast.Assert, f.line)
            repl = _fix_assert(node, lines) if node is not None else None
        elif f.code == "JG007":
            node = _node_at(tree, ast.Expr, f.line)
            repl = _fix_at_update(node, lines) if node is not None else None
        else:
            skipped.append(f.render())
            continue
        if repl is None:
            skipped.append(f.render())
            continue
        lines[node.lineno - 1: (node.end_lineno or node.lineno)] = repl
        fixed += 1
    if fixed:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines) + ("\n" if text.endswith("\n") else ""))
    return fixed, skipped


def _suppression_line(lines: List[str], lineno: int) -> int:
    """The physical line a suppression comment may legally land on: skip
    past backslash continuations (a comment after ``\\`` is a syntax
    error); any line of the statement's span suppresses (engine rule)."""
    i = lineno
    while i <= len(lines) and lines[i - 1].rstrip().endswith("\\"):
        i += 1
    return min(i, len(lines))


def _insert_suppressions(path: str, findings: List[engine.Finding],
                         justification: str) -> int:
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    lines = text.splitlines()
    by_line: Dict[int, set] = {}
    for f in findings:
        target = _suppression_line(lines, f.line)
        by_line.setdefault(target, set()).add(f.code)
    n = 0
    for lineno, codes in sorted(by_line.items()):
        line = lines[lineno - 1]
        m = _DISABLE_RE.search(line)
        if m:
            merged = {c.strip() for c in m.group(2).split(",") if c.strip()}
            merged |= codes
            lines[lineno - 1] = (line[: m.start(2)]
                                 + ",".join(sorted(merged))
                                 + line[m.end(2):])
        else:
            lines[lineno - 1] = (
                f"{line}  # jaxlint: disable={','.join(sorted(codes))}"
                f" -- {justification}"
            )
        n += len(codes)
    if n:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines) + ("\n" if text.endswith("\n") else ""))
    return n


def apply_fixes(report: engine.Report, root: Optional[str] = None,
                suppress: bool = False,
                justification: Optional[str] = None) -> FixResult:
    """Apply mechanical fixes for ``report``'s ACTIVE findings.

    Default mode rewrites the FIXABLE_CODES subset; ``suppress=True``
    instead inserts justified suppression comments for every active
    finding (``justification`` is then required)."""
    if suppress and not (justification or "").strip():
        raise ValueError(
            "suppression insertion requires a justification — a suppression "
            "that cannot say why is a bug tracker with the entries deleted"
        )
    root = os.path.abspath(root or os.getcwd())
    by_path: Dict[str, List[engine.Finding]] = {}
    for f in report.active:
        if f.code == "JG000":
            continue  # parse failures have no mechanical fix
        by_path.setdefault(f.path, []).append(f)
    rewritten = suppressed = 0
    skipped: List[str] = []
    files: List[str] = []
    for relpath, findings in sorted(by_path.items()):
        path = relpath if os.path.isabs(relpath) else os.path.join(root, relpath)
        if suppress:
            n = _insert_suppressions(path, findings, justification.strip())
            suppressed += n
            if n:
                files.append(relpath)
        else:
            fixable = [f for f in findings if f.code in FIXABLE_CODES]
            skipped.extend(f.render() for f in findings
                           if f.code not in FIXABLE_CODES)
            if not fixable:
                continue
            n, skip = _apply_rewrites(path, fixable)
            rewritten += n
            skipped.extend(skip)
            if n:
                files.append(relpath)
    return FixResult(rewritten, suppressed, skipped, files)
