"""jaxlint phase 1 — the project index.

PR 1's rules were deliberately scope-local; the hazards the ROADMAP queued
next (donation through ``functools.partial``/helper indirection, host
callbacks reached from timed regions, axis arities of functions defined a
module away) are whole-program properties. This module builds the picture a
single-file pass cannot see:

- a **module graph**: every analyzed file gets a dotted module name derived
  from its path, and its import map is absolutized against that name (so
  ``from .trainer import make_train_state`` inside
  ``gan_deeplearning4j_tpu.parallel`` resolves to
  ``gan_deeplearning4j_tpu.parallel.trainer.make_train_state``);
- a **symbol table** of top-level functions/classes/methods per module;
- a :class:`FunctionSummary` per function: positional parameters, which of
  them look like PRNG keys, whether the function is jit/shard_map-traced
  (directly, via decorator chains, or through ``functools.partial``), which
  ``donate_argnums`` it declares or returns from a builder, which resolved
  callables it calls, and whether it performs a host callback
  (``io_callback``/``pure_callback``/``jax.debug.*``) — with a transitive
  ("tainted") closure over the intra-project call graph;
- **module-level donators**: names bound at module scope to donating jitted
  callables, including ``name = make_step()`` where ``make_step`` is a
  builder imported from another module.

Phase 2 (the rules) receives the index as ``mod.project`` on every
:class:`~.engine.SourceModule`. Everything here is stdlib-only and purely
syntactic — the index records what is *statically visible*, and rules are
expected to treat absence of a summary as "unknown", never as "safe".
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Tuple

from gan_deeplearning4j_tpu.analysis import _common

_PRNG_PARAM_RE = re.compile(
    r"^(key|keys|rng|rngs|prng|prng_key|subkey|sub_key|seed_key)$"
)
_PRNG_SUFFIXES = ("_key", "_keys", "_rng", "_rngs")


def module_name_for_path(relpath: str) -> str:
    """Dotted module name for an engine-relative path:
    ``gan_deeplearning4j_tpu/harness/config.py`` ->
    ``gan_deeplearning4j_tpu.harness.config``; a package ``__init__.py``
    names the package itself; ``bench.py`` -> ``bench``."""
    norm = relpath.replace(os.sep, "/")
    if norm.endswith(".py"):
        norm = norm[:-3]
    parts = [p for p in norm.split("/") if p not in ("", ".")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def looks_like_prng_param(name: str) -> bool:
    low = name.lower()
    return bool(_PRNG_PARAM_RE.match(low)) or low.endswith(_PRNG_SUFFIXES)


def jit_donate_argnums(call: ast.Call, scope_body, resolve) -> Optional[tuple]:
    """``donate_argnums`` of a ``jax.jit``/``jax.pmap`` call, resolving both
    the literal kwarg and the ``**kwargs``-dict-literal builder idiom this
    repo uses (``kwargs = {"donate_argnums": (0,)} ... jax.jit(f, **kwargs)``
    — the dict may gain sharding entries after the donate entry)."""
    if not (isinstance(call, ast.Call) and resolve(call.func) in _common.JIT_WRAPPERS):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _common.literal_int_tuple(kw.value)
        if kw.arg is None and isinstance(kw.value, ast.Name) and scope_body:
            for stmt in scope_body:
                if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and stmt.targets[0].id == kw.value.id
                        and isinstance(stmt.value, ast.Dict)):
                    for k, v in zip(stmt.value.keys, stmt.value.values):
                        if (isinstance(k, ast.Constant)
                                and k.value == "donate_argnums"):
                            return _common.literal_int_tuple(v)
    return None


def _decorator_tracing_info(dec: ast.AST, resolve) -> Tuple[bool, Optional[tuple]]:
    """(is_traced, donate_argnums) for one decorator, seeing through
    ``@jax.jit``, ``@jax.jit(donate_argnums=...)`` and
    ``@functools.partial(jax.jit, donate_argnums=...)``."""
    if resolve(dec) in _common.TRACING_WRAPPERS:
        return True, None
    if isinstance(dec, ast.Call):
        r = resolve(dec.func)
        if r in _common.TRACING_WRAPPERS:
            nums = None
            if r in _common.JIT_WRAPPERS:
                for kw in dec.keywords:
                    if kw.arg == "donate_argnums":
                        nums = _common.literal_int_tuple(kw.value)
            return True, nums
        if r == "functools.partial" and dec.args:
            inner = resolve(dec.args[0])
            if inner in _common.TRACING_WRAPPERS:
                nums = None
                if inner in _common.JIT_WRAPPERS:
                    for kw in dec.keywords:
                        if kw.arg == "donate_argnums":
                            nums = _common.literal_int_tuple(kw.value)
                return True, nums
    return False, None


@dataclasses.dataclass
class FunctionSummary:
    """What phase 2 may assume about one function without re-reading it."""

    module: str
    qualname: str          # "train_step" or "Trainer.fit_round"
    name: str
    lineno: int
    params: Tuple[str, ...]          # positional params, self/cls stripped
    num_defaults: int
    is_method: bool
    traced: bool                     # jit/shard_map/... via decorator chain
    donates: Tuple[int, ...]         # donate_argnums from its own decorators
    returns_donation: Tuple[int, ...]  # builder: returns jax.jit(..., donate)
    prng_params: Tuple[str, ...]
    calls: Tuple[str, ...]           # resolved names this function calls
    has_host_callback: bool          # DIRECT io/pure_callback or jax.debug.*
    has_sync_io: bool = False        # DIRECT open/fsync/urlopen/socket...
    has_spawn: bool = False          # DIRECT subprocess.Popen/run/os.fork...
    node: ast.AST = dataclasses.field(repr=False, default=None)

    @property
    def min_arity(self) -> int:
        return len(self.params) - self.num_defaults

    @property
    def fq(self) -> str:
        return f"{self.module}.{self.qualname}"


@dataclasses.dataclass
class ModuleInfo:
    """Per-module slice of the index."""

    name: str
    path: str
    srcmod: object = dataclasses.field(repr=False, default=None)
    is_package: bool = False
    functions: Dict[str, FunctionSummary] = dataclasses.field(default_factory=dict)
    donators: Dict[str, Tuple[int, ...]] = dataclasses.field(default_factory=dict)
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)  # absolutized

    @property
    def package(self) -> str:
        """The package context relative imports resolve against."""
        if self.is_package:
            return self.name
        return self.name.rpartition(".")[0]


class ProjectIndex:
    """The cross-module picture, built once per analysis run (phase 1)."""

    def __init__(self, srcmods) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_path: Dict[str, ModuleInfo] = {}
        self._taint_cache: Dict[str, bool] = {}
        self._io_taint_cache: Dict[str, bool] = {}
        self._spawn_taint_cache: Dict[str, bool] = {}
        self._concurrency = None
        self._lifecycle = None
        for mod in srcmods:
            self._index_module(mod)
        # second pass: module-level donators that need every summary in place
        for info in self.modules.values():
            self._collect_donators(info)

    @property
    def concurrency(self):
        """The thread-safety extension (:mod:`.concurrency`), built lazily
        on first use so runs that exclude JG024–JG026 pay nothing for it;
        per-path summaries are cached inside the returned index."""
        if self._concurrency is None:
            from gan_deeplearning4j_tpu.analysis import concurrency as _conc

            self._concurrency = _conc.build(self)
        return self._concurrency

    @property
    def lifecycle(self):
        """The paired-resource extension (:mod:`.lifecycle`), built lazily
        on first use so runs that exclude JG027–JG029 pay nothing for it;
        per-path summaries are cached inside the returned index."""
        if self._lifecycle is None:
            from gan_deeplearning4j_tpu.analysis import lifecycle as _life

            self._lifecycle = _life.build(self)
        return self._lifecycle

    # -- construction -------------------------------------------------------
    def _index_module(self, mod) -> None:
        name = module_name_for_path(mod.path)
        info = ModuleInfo(
            name=name,
            path=mod.path,
            srcmod=mod,
            is_package=os.path.basename(mod.path) == "__init__.py",
        )
        info.imports = {
            local: self._absolutize(info, dotted)
            for local, dotted in mod.imports.items()
        }
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._summarize(info, mod, node, qualprefix="", is_method=False)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._summarize(info, mod, sub,
                                        qualprefix=node.name + ".",
                                        is_method=True)
        self.modules[name] = info
        self.by_path[mod.path] = info

    @staticmethod
    def _absolutize(info: ModuleInfo, dotted: str) -> str:
        """Resolve the import map's ``.``-prefixed relative targets against
        the importing module's package."""
        if not dotted.startswith("."):
            return dotted
        level = len(dotted) - len(dotted.lstrip("."))
        rest = dotted[level:]
        base_parts = info.package.split(".") if info.package else []
        # level 1 = the containing package; each extra dot climbs one
        base_parts = base_parts[: len(base_parts) - (level - 1)] if level > 1 else base_parts
        base = ".".join(p for p in base_parts if p)
        return f"{base}.{rest}" if base and rest else (base or rest)

    def _summarize(self, info: ModuleInfo, mod, fn, qualprefix: str,
                   is_method: bool) -> None:
        a = fn.args
        params = [p.arg for p in (a.posonlyargs + a.args)]
        if is_method and params and params[0] in ("self", "cls"):
            params = params[1:]
        traced, donates = False, None
        for dec in fn.decorator_list:
            t, d = _decorator_tracing_info(dec, mod.resolve)
            traced = traced or t
            donates = donates if d is None else d
        returns_donation: Optional[tuple] = None
        for ret in ast.walk(fn):
            if isinstance(ret, ast.Return) and ret.value is not None:
                nums = jit_donate_argnums(ret.value, fn.body, mod.resolve)
                if nums:
                    returns_donation = nums
        calls: List[str] = []
        has_cb = False
        has_io = False
        has_spawn = False
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            resolved = mod.resolve(n.func)
            if resolved in _common.HOST_CALLBACKS:
                has_cb = True
            if resolved in _common.SYNC_IO_CALLS:
                has_io = True
            if resolved in _common.SPAWN_CALLS:
                has_spawn = True
            if resolved is None:
                continue
            calls.append(self._canonical_call(info, resolved))
        summary = FunctionSummary(
            module=info.name,
            qualname=qualprefix + fn.name,
            name=fn.name,
            lineno=fn.lineno,
            params=tuple(params),
            num_defaults=len(a.defaults),
            is_method=is_method,
            traced=traced,
            donates=tuple(donates or ()),
            returns_donation=tuple(returns_donation or ()),
            prng_params=tuple(p for p in params if looks_like_prng_param(p)),
            calls=tuple(dict.fromkeys(calls)),
            has_host_callback=has_cb,
            has_sync_io=has_io,
            has_spawn=has_spawn,
            node=fn,
        )
        info.functions[summary.qualname] = summary

    def _canonical_call(self, info: ModuleInfo, resolved: str) -> str:
        """Normalize a resolved call target into an index-wide name:
        relative-import targets are absolutized against the module's
        package, imported names become absolute module paths, bare local
        names become ``<module>.<name>``; ``self.m`` attribute calls keep
        their surface form and are matched per-module later."""
        if resolved.startswith("."):
            # the import map's '.'-prefixed pseudo-root (from .steps import
            # step) — without this hop the name never matches the index
            return self._absolutize(info, resolved)
        first, _, rest = resolved.partition(".")
        if first == "self":
            return f"{info.name}.self.{rest}" if rest else resolved
        mapped = info.imports.get(first)
        if mapped is not None:
            return f"{mapped}.{rest}" if rest else mapped
        if "." not in resolved:
            return f"{info.name}.{resolved}"
        return resolved

    def _collect_donators(self, info: ModuleInfo) -> None:
        mod = info.srcmod
        for stmt in mod.tree.body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            target = stmt.targets[0].id
            nums = jit_donate_argnums(stmt.value, mod.tree.body, mod.resolve)
            if nums:
                info.donators[target] = nums
                continue
            # name = builder() where builder is a (possibly imported)
            # function that returns a donating jit
            if isinstance(stmt.value, ast.Call) and not stmt.value.args:
                summary = self.resolve_function(mod, stmt.value.func)
                if summary is not None and summary.returns_donation:
                    info.donators[target] = summary.returns_donation

    # -- lookups ------------------------------------------------------------
    def lookup(self, fq: str) -> Optional[FunctionSummary]:
        """Find a summary by canonical name (``pkg.mod.fn`` or
        ``pkg.mod.Class.method``) by longest-module-prefix match."""
        parts = fq.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            module = ".".join(parts[:cut])
            info = self.modules.get(module)
            if info is not None:
                qual = ".".join(parts[cut:])
                return info.functions.get(qual)
        return None

    def resolve_function(self, mod, node_or_name) -> Optional[FunctionSummary]:
        """Summary for a Name/Attribute expression in ``mod``'s namespace —
        local functions, imported functions, one re-export hop through a
        package ``__init__``."""
        info = self.by_path.get(mod.path)
        if info is None:
            return None
        if isinstance(node_or_name, str):
            dotted = node_or_name
        else:
            dotted = _common.dotted_name(node_or_name)
        if dotted is None:
            return None
        canonical = self._canonical_call(info, self._local_resolve(mod, dotted))
        found = self.lookup(canonical)
        if found is not None:
            return found
        # one re-export hop: pkg.__init__ imported the symbol from a submodule
        parts = canonical.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            pkg = self.modules.get(".".join(parts[:cut]))
            if pkg is not None and pkg.is_package:
                tail = ".".join(parts[cut:])
                head = tail.split(".")[0]
                re_target = pkg.imports.get(head)
                if re_target:
                    rest = tail[len(head) + 1:]
                    return self.lookup(
                        f"{re_target}.{rest}" if rest else re_target)
        return None

    @staticmethod
    def _local_resolve(mod, dotted: str) -> str:
        first, _, rest = dotted.partition(".")
        root = mod.imports.get(first)
        if root is None:
            return dotted
        return f"{root}.{rest}" if rest else root

    def imported_donator(self, mod, local_name: str) -> Optional[Tuple[int, ...]]:
        """donate_argnums for ``local_name`` in ``mod`` when it is a
        module-level donating callable imported from ANOTHER indexed module
        (``from pkg.mod import step``) — following package ``__init__``
        re-export hops (``from pkg import step`` where ``pkg/__init__``
        does ``from .steps import step``)."""
        info = self.by_path.get(mod.path)
        if info is None:
            return None
        target = info.imports.get(local_name)
        seen = set()
        while target and target not in seen:
            seen.add(target)
            owner_name, _, symbol = target.rpartition(".")
            owner = self.modules.get(owner_name)
            if owner is None or owner is info:
                return None
            nums = owner.donators.get(symbol)
            if nums:
                return nums
            target = owner.imports.get(symbol)  # re-export hop
        return None

    # -- transitive taints --------------------------------------------------
    def callback_tainted(self, summary: FunctionSummary) -> bool:
        """True when ``summary`` performs a host callback itself or reaches
        one through statically-resolvable project calls (fixpoint over the
        call graph; cycles resolve to False-unless-proven)."""
        return self._tainted(summary.fq, frozenset(),
                             "has_host_callback", self._taint_cache)

    def io_tainted(self, summary: FunctionSummary) -> bool:
        """Same closure, different mark: True when ``summary`` performs
        synchronous host I/O (open/fsync/urlopen/socket — the
        :data:`_common.SYNC_IO_CALLS` set) itself or reaches it through
        project calls. JG020's input: the checkpoint write two calls
        below a timed step loop is exactly what direct scanning misses."""
        return self._tainted(summary.fq, frozenset(),
                             "has_sync_io", self._io_taint_cache)

    def spawn_tainted(self, summary: FunctionSummary) -> bool:
        """Same closure, third mark: True when ``summary`` launches an OS
        process (the :data:`_common.SPAWN_CALLS` set) itself or reaches
        one through project calls. JG021's input: the relaunch helper a
        supervision loop calls is where the ``Popen`` actually lives."""
        return self._tainted(summary.fq, frozenset(),
                             "has_spawn", self._spawn_taint_cache)

    def _tainted(self, fq: str, seen: frozenset, mark: str,
                 cache: Dict[str, bool]) -> bool:
        if fq in cache:
            return cache[fq]
        if fq in seen:
            return False
        summary = self.lookup(fq)
        if summary is None:
            return False
        if getattr(summary, mark):
            cache[fq] = True
            return True
        seen = seen | {fq}
        for callee in summary.calls:
            target = callee
            # `self.m` calls match a method of any class in the same module
            marker = f"{summary.module}.self."
            if callee.startswith(marker):
                mname = callee[len(marker):]
                owner = self.modules[summary.module]
                target = None
                for qual, s in owner.functions.items():
                    if s.is_method and qual.endswith("." + mname):
                        target = f"{summary.module}.{qual}"
                        break
                if target is None:
                    continue
            if self.lookup(target) is not None and self._tainted(
                    target, seen, mark, cache):
                cache[fq] = True
                return True
        cache[fq] = False
        return False


def build_index(srcmods) -> ProjectIndex:
    """Phase-1 entry point used by the engine."""
    return ProjectIndex([m for m in srcmods if hasattr(m, "tree")])
