"""jaxlint phase 1½ — the concurrency index (thread-safety summaries).

PRs 11–15 made nearly every plane of this repo run its own threads: the
micro-batcher's worker/completer pair, the router's health loop, the
autoscaler tick, the reload controller, the alert evaluator, the engine
warm-up thread. Lock discipline across those planes was policed statically
in exactly two narrow slices (JG016's ``swap*`` classes, JG022's variant
tables) and dynamically by drills that only catch races probabilistically.
This module generalizes the static side: a whole-program summary of *who
runs on which thread* and *which lock each shared access sits under*, so
rules JG024–JG026 can check synchronization invariants mechanically.

Per analyzed module it discovers **thread entry points**:

- ``threading.Thread(target=self.m, ...)`` / ``threading.Timer(dt, self.m)``
  anywhere in a class (the daemon-loop-launched-in-``__init__``/``start``
  idiom every plane here uses) marks ``m`` as running on a spawned thread;
- ``run`` of a ``threading.Thread`` subclass;
- ``do_*``/``handle*`` methods of ``BaseHTTPRequestHandler`` subclasses
  (each request runs them on a ``ThreadingHTTPServer`` pool thread; the
  handler *instance* is per-request, so these mark the class as threaded
  without making its instance attributes shared state — see
  :attr:`ClassConcurrency.instance_shared`).

and computes a :class:`MethodConcurrency` per method: every ``self.<attr>``
load/store with the set of locks lexically held at that point (``with
self._lock:`` scopes; condition variables constructed over a lock alias to
that lock), the ordered lock-acquisition sequence with the held-set at each
acquisition, the same-class calls made with locks held (the one-hop lens
JG025/JG026 follow), and every known *blocking* call (JG017's network set,
``time.sleep``, thread/process ``.join``, ``subprocess``, device sync) with
the locks held around it. A call-site propagation pass marks private
helpers whose every in-class call site holds lock L as guarded-by-L, so
the ``_flush_locked``-style convention does not read as an escape.

Lock identities **unify across classes** where the sharing is statically
visible: a lock passed through a constructor (``Worker(lock=self._lock)``
where ``Worker.__init__`` does ``self._lk = lock``) and a lock planted by
attribute assignment (``worker._lk = self._lock`` on an object whose
class resolves) collapse into one canonical id in a project-wide
union-find, so JG025's acquisition graph spans planes instead of
stopping at the class boundary — the false-negative class the first
concurrency PR documented.

Everything is statically visible facts only. Known false-negative classes
(documented here once, referenced by the rules): ``.acquire()``/
``.release()`` pairs outside ``with`` are not tracked (the *lifecycle*
index owns that pairing — JG027/JG028); module-global state shared by
module-level thread targets is not modeled (only classes are); locks
reached through cross-class attribute chains (``self.registry.lock`` vs
the registry's own ``self.lock``) unify only via the constructor/
assignment routes above, not by chained attribute typing; nested
``def``/``lambda`` bodies are separate scopes (a closure may run on
another thread after the ``with`` exited — the same rule JG022 applies).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from gan_deeplearning4j_tpu.analysis import _common
from gan_deeplearning4j_tpu.analysis.rules.net_timeout import NETWORK_CALLS

#: with-context attribute names that count as a lock even without "lock"
#: in the name (JG016's set, kept in sync)
LOCK_NAMES = {"_cv", "cv", "_cond", "cond", "_condition", "condition",
              "_mutex", "mutex"}

#: threading constructors whose instances are locks (assignment to
#: ``self.<attr>`` in any method makes ``<attr>`` a known lock attribute)
_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}

#: method names on a container that mutate it — a load of ``self._queue``
#: that feeds ``.append`` is a *mutating use* even though the attribute is
#: never rebound. Only counted on attributes initialized to a container
#: (literal or known ctor): ``self.watcher.discard(...)`` on a domain
#: object shares a name with ``set.discard`` but mutates no shared dict.
_MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "update", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "setdefault", "sort",
    "reverse", "put", "put_nowait",
}

#: resolved constructors whose result is a mutable container
_CONTAINER_CTORS = {
    "list", "dict", "set", "collections.deque", "collections.defaultdict",
    "collections.Counter", "collections.OrderedDict", "queue.Queue",
    "queue.SimpleQueue", "queue.PriorityQueue", "queue.LifoQueue",
}

#: resolved callables that block the calling thread (JG026's direct set):
#: JG017's network calls, process spawns, sleeps, and device sync
BLOCKING_CALLS = (
    set(NETWORK_CALLS)
    | _common.SPAWN_CALLS
    | {"time.sleep", "jax.block_until_ready",
       "subprocess.Popen.wait", "os.waitpid"}
)


@dataclasses.dataclass
class Access:
    """One ``self.<attr>`` touch inside a method."""

    attr: str
    node: ast.AST
    method: str
    is_store: bool       # rebind / aug-assign / subscript-store target
    is_mutating: bool    # is_store OR a mutator-method call on the attr
    held: FrozenSet[str]  # canonical lock ids lexically held at the access


@dataclasses.dataclass
class LockAcquisition:
    """One ``with <lock>:`` entry, with what was already held."""

    lock: str
    node: ast.AST
    method: str
    held_before: FrozenSet[str]


@dataclasses.dataclass
class SelfCall:
    """A ``self.m(...)`` call site, with the locks held around it."""

    callee: str
    node: ast.AST
    method: str
    held: FrozenSet[str]


@dataclasses.dataclass
class BlockingCall:
    """A known-blocking call, with the locks held around it."""

    label: str
    node: ast.AST
    method: str
    held: FrozenSet[str]


@dataclasses.dataclass
class MethodConcurrency:
    """What the rules may assume about one method without re-reading it."""

    name: str
    node: ast.AST
    accesses: List[Access] = dataclasses.field(default_factory=list)
    acquisitions: List[LockAcquisition] = dataclasses.field(
        default_factory=list)
    self_calls: List[SelfCall] = dataclasses.field(default_factory=list)
    blocking: List[BlockingCall] = dataclasses.field(default_factory=list)
    #: locks every in-class call site provably holds (call-site propagation)
    caller_held: FrozenSet[str] = frozenset()


@dataclasses.dataclass
class ClassConcurrency:
    """Per-class (or per-module-scope) concurrency summary."""

    name: str
    path: str
    node: Optional[ast.AST]
    methods: Dict[str, MethodConcurrency] = dataclasses.field(
        default_factory=dict)
    #: method name -> how it becomes a thread entry ("thread-target",
    #: "timer", "run-override", "http-handler")
    entry_points: Dict[str, str] = dataclasses.field(default_factory=dict)
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    #: Condition-over-lock / rebinding aliases, attr -> canonical attr
    lock_aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: attrs initialized to a mutable container — the only attrs a
    #: ``.append``-style call counts as mutating
    container_attrs: Set[str] = dataclasses.field(default_factory=set)
    #: False for BaseHTTPRequestHandler subclasses: instances are
    #: per-request, so ``self.<attr>`` is NOT cross-thread shared state
    instance_shared: bool = True
    #: ``__init__`` positional parameter names (self excluded), for
    #: matching constructor-injection call sites positionally
    init_params: List[str] = dataclasses.field(default_factory=list)
    #: ``__init__`` param name -> ``self`` attr it is forwarded into
    #: (``self._lk = lock``) — the receiving half of lock injection
    init_param_attrs: Dict[str, str] = dataclasses.field(
        default_factory=dict)
    #: ``self`` attr -> resolved constructor dotted name (``self.worker =
    #: Worker(...)``), for typing ``self.worker._lk = ...`` assignments
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)

    def canonical_lock(self, attr: str) -> str:
        seen = set()
        while attr in self.lock_aliases and attr not in seen:
            seen.add(attr)
            attr = self.lock_aliases[attr]
        return attr

    def lock_id(self, attr: str) -> str:
        """Index-wide id for a ``self.<attr>`` lock of this class."""
        return f"{self.name}.{self.canonical_lock(attr)}"

    # -- thread contexts ---------------------------------------------------
    def call_closure(self, start: str) -> Set[str]:
        """``start`` plus every same-class method reachable from it."""
        out, stack = set(), [start]
        while stack:
            m = stack.pop()
            if m in out:
                continue
            out.add(m)
            mc = self.methods.get(m)
            if mc is not None:
                stack.extend(c.callee for c in mc.self_calls
                             if c.callee in self.methods)
        return out

    def thread_contexts(self) -> List[Tuple[str, Set[str]]]:
        """(label, method set) per concurrent context: one per spawned
        entry point, plus ``<caller>`` for everything not exclusively
        reached from a spawned thread (public API runs on whatever thread
        calls it). Empty when the class spawns nothing."""
        if not self.entry_points:
            return []
        ctxs: List[Tuple[str, Set[str]]] = []
        covered: Set[str] = set()
        for ep in sorted(self.entry_points):
            closure = self.call_closure(ep)
            ctxs.append((ep, closure))
            covered |= closure
        external = set(self.methods) - covered - {"__init__"}
        if external:
            ctxs.append(("<caller>", external))
        return ctxs


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_lockish_name(name: str) -> bool:
    return "lock" in name.lower() or name in LOCK_NAMES


class ConcurrencyIndex:
    """Lazy per-path cache of :class:`ClassConcurrency` summaries. Built
    from the project index's parsed modules on first use by a rule, so
    runs that exclude JG024–JG026 pay nothing for it."""

    def __init__(self, project) -> None:
        self._project = project
        self._cache: Dict[str, List[ClassConcurrency]] = {}
        self._lock_parent: Optional[dict] = None  # union-find forest
        self._global_edges: Optional[dict] = None
        #: (path, class name) -> attrs taught to be locks by cross-class
        #: plants (``worker._lk = self._lock`` where ``_lk`` is never
        #: constructed locally)
        self._extra_locks: Dict[tuple, Set[str]] = {}
        self._new_extras: Dict[tuple, Set[str]] = {}

    def classes(self, path: str) -> List[ClassConcurrency]:
        """Summaries for every class in ``path`` (nested classes included)
        plus one module-scope pseudo-entry holding the module-level
        functions (for lock-order analysis over module-global locks)."""
        if path not in self._cache:
            info = self._project.by_path.get(path)
            extras = {cls: attrs for (p, cls), attrs
                      in self._extra_locks.items() if p == path}
            self._cache[path] = (
                [] if info is None
                else _build_module(info.srcmod, extras))
        return self._cache[path]

    # -- cross-class lock unification ---------------------------------------
    # Lock ids are per-module pairs ``(module_name, short_id)`` so two
    # unrelated classes that happen to share a name never collide; the
    # union-find collapses pairs that provably alias ONE runtime lock:
    # constructor injection (``Worker(lock=self._lock)`` forwarded into
    # ``self._lk``) and attribute planting (``worker._lk = self._lock``
    # on an object whose class resolves through the project index).

    def _all(self) -> List[tuple]:
        out = []
        for path in sorted(self._project.by_path):
            info = self._project.by_path[path]
            for cc in self.classes(path):
                out.append((info, cc))
        return out

    def _find(self, key: tuple) -> tuple:
        p = self._lock_parent
        while p.get(key, key) != key:
            p[key] = p.get(p[key], p[key])  # path halving
            key = p[key]
        return key

    def _union(self, a: tuple, b: tuple) -> None:
        ra, rb = self._find(a), self._find(b)
        if ra == rb:
            return
        # deterministic root: lexicographically smallest key wins, so
        # canonical ids are stable across module orderings
        root, child = (ra, rb) if ra <= rb else (rb, ra)
        self._lock_parent[child] = root

    def canonical(self, module_name: str, lock_id: str) -> tuple:
        """Project-wide canonical identity of a per-module lock id."""
        self._ensure_unified()
        return self._find((module_name, lock_id))

    def _ensure_unified(self) -> None:
        if self._lock_parent is not None:
            return
        # a plant can TEACH a class that an attr it never constructs is
        # a lock (``worker._lk = self._lock`` into ``self._lk = None``) —
        # its summary must be rebuilt so ``with self._lk:`` registers as
        # an acquisition, then the scan repeats; bounded because plants
        # of planted locks are rare and each round only adds attrs
        for _ in range(4):
            self._lock_parent = {}
            self._new_extras = {}
            everything = self._all()
            class_map: Dict[str, tuple] = {}
            for info, cc in everything:
                if cc.node is not None:
                    class_map[f"{info.name}.{cc.name}"] = (info, cc)
            for info, cc in everything:
                encl = cc if cc.node is not None else None
                for name in sorted(cc.methods):
                    self._scan_sharing(info, encl, cc.methods[name].node,
                                       class_map)
            fresh = {k: v - self._extra_locks.get(k, set())
                     for k, v in self._new_extras.items()}
            fresh = {k: v for k, v in fresh.items() if v}
            if not fresh:
                break
            for key, attrs in fresh.items():
                self._extra_locks.setdefault(key, set()).update(attrs)
                self._cache.pop(key[0], None)

    def _resolve_class(self, info, func_expr: ast.AST,
                       class_map: dict) -> Optional[tuple]:
        resolved = info.srcmod.resolve(func_expr)
        if resolved is None:
            return None
        canon = self._project._canonical_call(info, resolved)
        return class_map.get(canon)

    def _lock_expr_id(self, info, encl: Optional[ClassConcurrency],
                      expr: ast.AST) -> Optional[tuple]:
        """(module, short_id) when ``expr`` denotes a known lock in the
        enclosing scope, else None."""
        attr = _self_attr(expr)
        if attr is not None:
            if encl is not None and (attr in encl.lock_attrs
                                     or _is_lockish_name(attr)):
                return (info.name, encl.lock_id(attr))
            return None
        if isinstance(expr, ast.Name) and _is_lockish_name(expr.id):
            return (info.name, expr.id)
        return None

    def _scan_sharing(self, info, encl, fn, class_map: dict) -> None:
        # local var -> (info, cc) of its constructed class, in source
        # order (good enough: sharing sites follow their constructions)
        local_types: Dict[str, tuple] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(node.value, ast.Call) and isinstance(
                        tgt, ast.Name):
                    owner = self._resolve_class(info, node.value.func,
                                                class_map)
                    if owner is not None:
                        local_types[tgt.id] = owner
                if isinstance(tgt, ast.Attribute):
                    lock = self._lock_expr_id(info, encl, node.value)
                    if lock is not None:
                        owner = self._owner_of(tgt.value, local_types,
                                               info, encl, class_map)
                        if owner is not None:
                            oinfo, occ = owner
                            self._union(lock, (oinfo.name,
                                               occ.lock_id(tgt.attr)))
                            if tgt.attr not in occ.lock_attrs:
                                self._new_extras.setdefault(
                                    (oinfo.path, occ.name),
                                    set()).add(tgt.attr)
            if isinstance(node, ast.Call):
                target = self._resolve_class(info, node.func, class_map)
                if target is None:
                    continue
                tinfo, tcc = target
                for i, arg in enumerate(node.args):
                    if i < len(tcc.init_params):
                        self._unify_arg(info, encl, arg, tinfo, tcc,
                                        tcc.init_params[i])
                for kw in node.keywords:
                    if kw.arg is not None:
                        self._unify_arg(info, encl, kw.value, tinfo, tcc,
                                        kw.arg)

    def _unify_arg(self, info, encl, arg, tinfo, tcc, param: str) -> None:
        lock = self._lock_expr_id(info, encl, arg)
        attr = tcc.init_param_attrs.get(param)
        if lock is None or attr is None:
            return
        self._union(lock, (tinfo.name, tcc.lock_id(attr)))

    def _owner_of(self, expr, local_types, info, encl,
                  class_map) -> Optional[tuple]:
        """(info, cc) of the class of ``expr`` (a receiver being planted
        with a lock): a local constructed in this function, or a ``self``
        attr the enclosing class constructed."""
        if isinstance(expr, ast.Name):
            return local_types.get(expr.id)
        attr = _self_attr(expr)
        if attr is not None and encl is not None:
            ctor = encl.attr_types.get(attr)
            if ctor is not None:
                canon = self._project._canonical_call(info, ctor)
                return class_map.get(canon)
        return None

    def global_lock_edges(self) -> dict:
        """The project-wide acquisition graph over canonical lock ids:
        ``(A, B) -> (path, node, where)`` of the first site that takes B
        while holding A (lexical nesting plus the one-hop same-class call
        lens). Deterministic: modules in sorted-path order, methods
        sorted, so "first" is stable across runs."""
        self._ensure_unified()
        if self._global_edges is not None:
            return self._global_edges
        edges: dict = {}

        def add(mname, path, held, lock, node, where):
            lk = self._find((mname, lock))
            for h in held:
                hh = self._find((mname, h))
                if hh != lk and (hh, lk) not in edges:
                    edges[(hh, lk)] = (path, node, where)

        for info, cc in self._all():
            for name in sorted(cc.methods):
                mc = cc.methods[name]
                for acq in mc.acquisitions:
                    add(info.name, info.path, acq.held_before, acq.lock,
                        acq.node, f"{cc.name}.{name}")
                for call in mc.self_calls:
                    if not call.held:
                        continue
                    callee = cc.methods.get(call.callee)
                    if callee is None:
                        continue
                    for acq in callee.acquisitions:
                        add(info.name, info.path, call.held, acq.lock,
                            call.node, f"{cc.name}.{name} -> {call.callee}")
        self._global_edges = edges
        return edges


def build(project) -> ConcurrencyIndex:
    return ConcurrencyIndex(project)


# -- construction -----------------------------------------------------------

def _build_module(mod, extra_locks=None) -> List[ClassConcurrency]:
    out: List[ClassConcurrency] = []
    class_nodes: List[ast.ClassDef] = [
        n for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)]
    for cls in class_nodes:
        out.append(_build_class(
            mod, cls, (extra_locks or {}).get(cls.name, set())))
    # module-scope pseudo-class: top-level functions + module locks, so
    # JG025 sees ``with _capture_lock:`` nesting outside any class
    scope = ClassConcurrency(name="<module>", path=mod.path, node=None)
    for n in mod.tree.body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope.methods[n.name] = _analyze_function(mod, scope, n)
    if scope.methods:
        out.append(scope)
    return out


def _build_class(mod, cls: ast.ClassDef,
                 extra_locks=frozenset()) -> ClassConcurrency:
    cc = ClassConcurrency(name=cls.name, path=mod.path, node=cls)
    # attrs taught to be locks by cross-class plants (unification pass)
    cc.lock_attrs.update(extra_locks)
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    method_names = {m.name for m in methods}

    # base classes: Thread subclasses run ``run`` on a spawned thread;
    # HTTP handler subclasses run ``do_*`` on server pool threads with a
    # fresh instance per request
    for base in cls.bases:
        resolved = mod.resolve(base) or ""
        if resolved == "threading.Thread" and "run" in method_names:
            cc.entry_points["run"] = "run-override"
        if resolved.endswith("BaseHTTPRequestHandler"):
            cc.instance_shared = False
            for m in method_names:
                if m.startswith("do_") or m.startswith("handle"):
                    cc.entry_points[m] = "http-handler"

    # lock attributes + aliases, from assignments in any method
    for m in methods:
        for node in ast.walk(m):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            attr = _self_attr(node.targets[0])
            if attr is None:
                continue
            if isinstance(node.value, (ast.List, ast.Dict, ast.Set,
                                       ast.ListComp, ast.DictComp,
                                       ast.SetComp)):
                cc.container_attrs.add(attr)
            if isinstance(node.value, ast.Call):
                ctor = mod.resolve(node.value.func)
                if ctor in _CONTAINER_CTORS:
                    cc.container_attrs.add(attr)
                if ctor in _LOCK_CTORS:
                    cc.lock_attrs.add(attr)
                    # Condition(self._lock): holding the condition IS
                    # holding the lock — alias them
                    if (ctor == "threading.Condition" and node.value.args):
                        inner = _self_attr(node.value.args[0])
                        if inner is not None:
                            cc.lock_aliases[attr] = inner
                            cc.lock_attrs.add(inner)
            other = _self_attr(node.value)
            if other is not None and (other in cc.lock_attrs
                                      or _is_lockish_name(other)):
                cc.lock_aliases[attr] = other

    # __init__ signature + param->attr forwarding and attr constructor
    # types — the raw material of cross-class lock unification
    for m in methods:
        if m.name == "__init__":
            names = [a.arg for a in m.args.posonlyargs + m.args.args]
            if names and names[0] in ("self", "cls"):
                names = names[1:]
            cc.init_params = names
            valid = set(names) | {a.arg for a in m.args.kwonlyargs}
            for node in ast.walk(m):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.value, ast.Name)
                        and node.value.id in valid):
                    attr = _self_attr(node.targets[0])
                    if attr is not None:
                        cc.init_param_attrs[node.value.id] = attr
                        # a lockish PARAM forwarded into any attr makes
                        # that attr a lock (``self._lk = lock``) — the
                        # receiving half of constructor injection
                        if _is_lockish_name(node.value.id):
                            cc.lock_attrs.add(attr)
        for node in ast.walk(m):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                attr = _self_attr(node.targets[0])
                ctor = mod.resolve(node.value.func)
                if attr is not None and ctor is not None:
                    cc.attr_types.setdefault(attr, ctor)

    # spawned-thread entry points: Thread(target=self.m) / Timer(dt, self.m)
    for m in methods:
        for node in ast.walk(m):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func)
            target = None
            if resolved == "threading.Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = _self_attr(kw.value)
                if node.args:
                    target = target or _self_attr(node.args[0])
                kind = "thread-target"
            elif resolved == "threading.Timer":
                for kw in node.keywords:
                    if kw.arg == "function":
                        target = _self_attr(kw.value)
                if len(node.args) >= 2:
                    target = target or _self_attr(node.args[1])
                kind = "timer"
            else:
                continue
            if target is not None and target in method_names:
                cc.entry_points.setdefault(target, kind)

    for m in methods:
        cc.methods[m.name] = _analyze_function(mod, cc, m)
    _propagate_callsite_guards(cc)
    return cc


def _lock_id_for_context(cc: ClassConcurrency,
                         expr: ast.AST) -> Optional[str]:
    """Canonical lock id for a ``with`` context expression, else None.
    ``self.<attr>`` locks are class-qualified; other expressions (module
    globals, ``registry.lock``) use their source text."""
    attr = _self_attr(expr)
    if attr is not None:
        if attr in cc.lock_attrs or _is_lockish_name(attr):
            return cc.lock_id(attr)
        return None
    if isinstance(expr, ast.Name) and _is_lockish_name(expr.id):
        return expr.id
    if isinstance(expr, ast.Attribute) and _is_lockish_name(expr.attr):
        try:
            return ast.unparse(expr)
        except Exception:  # pragma: no cover - unparse handles these
            return None
    return None


def _blocking_label(mod, node: ast.Call) -> Optional[str]:
    """Label when ``node`` is a known-blocking call, else None."""
    resolved = mod.resolve(node.func)
    if resolved in BLOCKING_CALLS:
        return resolved
    if isinstance(node.func, ast.Attribute):
        name = node.func.attr
        if name == "block_until_ready":
            return ".block_until_ready"
        if name == "join":
            # thread/process join, not str.join: str.join always takes the
            # iterable positionally, so no-arg / numeric-timeout / kwarg
            # shapes are unambiguous
            base_is_str = (isinstance(node.func.value, ast.Constant)
                           and isinstance(node.func.value.value, str))
            numeric = (len(node.args) == 1
                       and isinstance(node.args[0], ast.Constant)
                       and isinstance(node.args[0].value, (int, float)))
            timeout_kw = any(kw.arg == "timeout" for kw in node.keywords)
            if not base_is_str and (not node.args or numeric or timeout_kw):
                return ".join"
    return None


def _analyze_function(mod, cc: ClassConcurrency,
                      fn) -> MethodConcurrency:
    mc = MethodConcurrency(name=fn.name, node=fn)

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return  # closures are separate scopes (may run on any thread)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                visit(item.context_expr, held)
                lock = _lock_id_for_context(cc, item.context_expr)
                if lock is not None:
                    mc.acquisitions.append(LockAcquisition(
                        lock=lock, node=item.context_expr, method=fn.name,
                        held_before=held))
                    acquired.append(lock)
            inner = held | frozenset(acquired)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, ast.Call):
            label = _blocking_label(mod, node)
            if label is not None:
                mc.blocking.append(BlockingCall(
                    label=label, node=node, method=fn.name, held=held))
            if isinstance(node.func, ast.Attribute):
                base_attr = _self_attr(node.func.value)
                if (base_attr is not None and node.func.attr in _MUTATORS
                        and base_attr in cc.container_attrs):
                    # self._queue.append(x): mutating use of _queue
                    mc.accesses.append(Access(
                        attr=base_attr, node=node.func.value,
                        method=fn.name, is_store=False, is_mutating=True,
                        held=held))
                    for arg in node.args:
                        visit(arg, held)
                    for kw in node.keywords:
                        visit(kw.value, held)
                    return
                callee = _self_attr(node.func)
                if callee is not None:
                    mc.self_calls.append(SelfCall(
                        callee=callee, node=node, method=fn.name,
                        held=held))
        if isinstance(node, ast.Subscript):
            attr = _self_attr(node.value)
            if attr is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
                # self._tbl[k] = v: mutating use of _tbl
                mc.accesses.append(Access(
                    attr=attr, node=node.value, method=fn.name,
                    is_store=False, is_mutating=True, held=held))
                visit(node.slice, held)
                return
        attr = _self_attr(node)
        if attr is not None:
            is_store = isinstance(node.ctx, (ast.Store, ast.Del))
            mc.accesses.append(Access(
                attr=attr, node=node, method=fn.name,
                is_store=is_store, is_mutating=is_store, held=held))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, frozenset())
    return mc


def _propagate_callsite_guards(cc: ClassConcurrency) -> None:
    """Fixpoint: a private (or ``*_locked``) non-entry method whose EVERY
    in-class call site holds lock L is itself guarded by L — its accesses
    are not escapes. Public methods never inherit guards (they are
    callable from anywhere, lockless)."""
    sites: Dict[str, List[SelfCall]] = {}
    for mc in cc.methods.values():
        for call in mc.self_calls:
            if call.callee in cc.methods:
                sites.setdefault(call.callee, []).append(call)
    for _ in range(len(cc.methods) or 1):
        changed = False
        for name, mc in cc.methods.items():
            if name in cc.entry_points:
                continue
            if not (name.startswith("_") or name.endswith("_locked")):
                continue
            own = sites.get(name)
            if not own:
                continue
            inter: Optional[FrozenSet[str]] = None
            for call in own:
                eff = call.held | cc.methods[call.method].caller_held
                inter = eff if inter is None else (inter & eff)
            inter = inter or frozenset()
            if inter != mc.caller_held:
                mc.caller_held = inter
                changed = True
        if not changed:
            break
