"""jaxlint engine: file walking, suppression comments, baseline, reporting.

Fingerprints are content-based — ``sha1(rule|path|normalized source line|
neighbor-context hash)`` — so a baseline entry survives unrelated edits
that shift line numbers, and goes stale (reported as such) the moment the
offending line itself (or its immediate neighborhood) changes. The
neighbor-context component disambiguates two textually identical lines in
one file; entries written under the older line-only scheme still match
(legacy fallback) and are auto-migrated to the current scheme by the CLI
on first run. Every baseline entry must carry a human ``justification``;
the engine refuses entries without one, so "baseline it" can never
silently become "ignore it".

The incremental cache (:class:`ParseCache`) persists parsed modules —
AST, suppression table, import map — keyed by per-file content hash, so
repeat runs (and ``--changed-only`` runs, which parse the FULL target set
for project-index fidelity but run rules only on the changed files) skip
the parse phase for unchanged files. Rules always re-run: findings are
cross-module facts and caching them per-file would be wrong the moment an
edit in one file changes what a rule reports about another.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import pickle
import re
import time
import tokenize
from typing import Iterable, List, Optional

from gan_deeplearning4j_tpu.analysis import _common

DEFAULT_BASELINE_PATH = os.path.join(os.path.dirname(__file__), "_baseline.json")

#: bump to invalidate every ParseCache entry (pickle layout, SourceModule
#: fields, suppression scanning — anything that changes parsed artifacts)
CACHE_VERSION = 1

# directories never worth descending into
_SKIP_DIRS = {".git", "__pycache__", ".jax_cache", "artifacts", ".pytest_cache",
              "node_modules", ".eggs", "build", "dist"}

_SUPPRESS_RE = re.compile(r"jaxlint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic. ``path`` is the engine-relative path (repo-relative
    when run from the repo root — the convention the checked-in baseline
    and the tier-1 test both use)."""

    code: str
    message: str
    path: str
    line: int
    col: int
    snippet: str
    #: hash of the nearest non-blank neighbor lines (above + below),
    #: normalized — disambiguates identical offending lines in one file
    #: without re-introducing raw line numbers. "" for findings built
    #: outside a SourceModule (parse failures, direct construction).
    context: str = ""

    @property
    def fingerprint(self) -> str:
        norm = " ".join(self.snippet.split())
        digest = hashlib.sha1(
            f"{self.code}|{self.path}|{norm}|{self.context}".encode()
        ).hexdigest()
        return digest[:16]

    @property
    def legacy_fingerprint(self) -> str:
        """The pre-context scheme — matched as a fallback so baselines
        written before the migration keep working, then rewritten."""
        norm = " ".join(self.snippet.split())
        digest = hashlib.sha1(
            f"{self.code}|{self.path}|{norm}".encode()
        ).hexdigest()
        return digest[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"

    def to_json(self) -> dict:
        return {**dataclasses.asdict(self), "fingerprint": self.fingerprint}


@dataclasses.dataclass
class SourceModule:
    """Parsed module handed to every rule."""

    path: str
    text: str
    tree: ast.AST
    lines: List[str]
    suppressions: dict  # line number -> set of codes (or {"all"})
    imports: dict  # local name -> dotted prefix (see _common.build_import_map)
    is_test: bool
    project: Optional[object] = None  # ProjectIndex, set by analyze_modules

    def resolve(self, node: ast.AST) -> Optional[str]:
        return _common.resolve_name(node, self.imports)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, code: str, message: str, node: ast.AST) -> Finding:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            code=code,
            message=message,
            path=self.path,
            line=lineno,
            col=col,
            snippet=self.line_text(lineno).strip(),
            context=self._neighbor_context(lineno),
        )

    def _neighbor_context(self, lineno: int) -> str:
        """Short hash of the nearest non-blank line above and below
        ``lineno`` (whitespace-normalized). Blank lines are skipped so a
        spacing-only edit does not stale a fingerprint; edits to the
        actual surrounding code do."""
        def nearest(rng) -> str:
            for ln in rng:
                text = " ".join(self.line_text(ln).split())
                if text:
                    return text
            return ""
        above = nearest(range(lineno - 1, 0, -1))
        below = nearest(range(lineno + 1, len(self.lines) + 1))
        return hashlib.sha1(f"{above}\n{below}".encode()).hexdigest()[:8]

    def suppressed(self, finding: Finding, node: ast.AST = None) -> bool:
        """A ``# jaxlint: disable=JG00x`` on the finding's line — or, when
        the node spans several physical lines, any line of the span."""
        start = finding.line
        end = getattr(node, "end_lineno", None) or start
        for ln in range(start, end + 1):
            codes = self.suppressions.get(ln)
            if codes and ("all" in codes or finding.code in codes):
                return True
        return False


@dataclasses.dataclass
class Report:
    """Partitioned analysis result. ``active`` is what gates CI; so do stale
    baseline entries (:attr:`gate_ok`) — a baseline that matches nothing is
    a fixed bug still being excused, and carrying it silently would let the
    next occurrence of the same fingerprint slip through."""

    active: List[Finding]
    suppressed: List[Finding]
    baselined: List[Finding]
    stale_baseline: List[dict]  # baseline entries that matched nothing
    files: int
    warnings: List[str] = dataclasses.field(default_factory=list)
    # wall-time breakdown: {"phases": {...}, "rules": {code: seconds},
    # "cache": {"hits": .., "misses": ..} when a ParseCache was used}.
    # Deliberately NOT part of to_json()/render_text() — timings vary run
    # to run and every emission format must be byte-stable for identical
    # inputs. The CLI renders it separately under --profile.
    profile: Optional[dict] = None
    #: legacy fingerprint -> current fingerprint, for baseline entries
    #: that matched only under the pre-context scheme; the CLI rewrites
    #: the baseline file from this map (auto-migration). Not part of
    #: to_json() — it describes the baseline FILE, not the tree.
    baseline_migrations: dict = dataclasses.field(default_factory=dict)
    #: the run's ProjectIndex (transient — CLI-side consumers like
    #: ``--lifecycle-stats`` read it; never serialized)
    index: Optional[object] = None

    @property
    def clean(self) -> bool:
        return not self.active

    @property
    def gate_ok(self) -> bool:
        """What CI keys on: no active findings AND no stale baseline."""
        return self.clean and not self.stale_baseline

    def to_json(self) -> dict:
        return {
            "clean": self.clean,
            "gate_ok": self.gate_ok,
            "files": self.files,
            "active": [f.to_json() for f in self.active],
            "suppressed": [f.to_json() for f in self.suppressed],
            "baselined": [f.to_json() for f in self.baselined],
            "stale_baseline": self.stale_baseline,
            "warnings": self.warnings,
        }

    def render_text(self) -> str:
        out = [f.render() for f in self.active]
        for entry in self.stale_baseline:
            out.append(
                f"# stale baseline entry {entry.get('fingerprint')} "
                f"({entry.get('rule')} {entry.get('path')}) — offending line "
                f"changed or was fixed; remove it from the baseline "
                f"(or run --prune-baseline)"
            )
        for w in self.warnings:
            out.append(f"# warning: {w}")
        out.append(
            f"# jaxlint: {self.files} files, {len(self.active)} active, "
            f"{len(self.suppressed)} suppressed, "
            f"{len(self.baselined)} baselined"
        )
        return "\n".join(out)


def _scan_suppressions(text: str) -> dict:
    """Line -> codes from ``# jaxlint: disable=...`` comments, via tokenize
    (comments only — the pattern inside a string literal does not count);
    regex fallback for files tokenize rejects."""
    supp: dict = {}

    def record(lineno: int, raw: str) -> None:
        m = _SUPPRESS_RE.search(raw)
        if not m:
            return
        codes = {c.strip().upper() if c.strip().lower() != "all" else "all"
                 for c in m.group(1).split(",") if c.strip()}
        if codes:
            supp.setdefault(lineno, set()).update(codes)

    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                record(tok.start[0], tok.string)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, line in enumerate(text.splitlines(), 1):
            if "#" in line:
                record(i, line[line.index("#"):])
    return supp


def _looks_like_test(relpath: str) -> bool:
    parts = relpath.replace(os.sep, "/").split("/")
    base = parts[-1]
    return (
        "tests" in parts[:-1]
        or base.startswith("test_")
        or base == "conftest.py"
    )


def parse_module(text: str, relpath: str, is_test: Optional[bool] = None):
    """SourceModule, or a parse-failure Finding (code JG000)."""
    try:
        tree = ast.parse(text)
    except SyntaxError as exc:
        return Finding(
            code="JG000",
            message=f"could not parse: {exc.msg}",
            path=relpath,
            line=exc.lineno or 1,
            col=exc.offset or 0,
            snippet="",
        )
    return SourceModule(
        path=relpath,
        text=text,
        tree=tree,
        lines=text.splitlines(),
        suppressions=_scan_suppressions(text),
        imports=_common.build_import_map(tree),
        is_test=_looks_like_test(relpath) if is_test is None else is_test,
    )


def collect_files(paths: Iterable[str], root: Optional[str] = None) -> List[str]:
    """Expand files/directories into a sorted list of .py paths, relative to
    ``root`` (default: cwd) when possible — relative paths keep fingerprints
    machine-independent. A path that is neither an existing directory nor an
    existing ``.py`` file raises: a typo in a CI invocation must fail the
    gate loudly, not shrink it to the paths that happened to resolve."""
    root = os.path.abspath(root or os.getcwd())
    found = []
    for p in paths:
        ap = os.path.abspath(os.path.join(root, p) if not os.path.isabs(p) else p)
        if not (os.path.isdir(ap) or (os.path.isfile(ap) and ap.endswith(".py"))):
            raise FileNotFoundError(
                f"jaxlint target {p!r} is neither a directory nor an "
                f"existing .py file (resolved to {ap})"
            )
        if os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        found.append(os.path.join(dirpath, fn))
        elif ap.endswith(".py"):
            found.append(ap)
    rel = []
    for ap in found:
        try:
            rp = os.path.relpath(ap, root)
        except ValueError:  # different drive (windows) — keep absolute
            rp = ap
        rel.append(rp if not rp.startswith("..") else ap)
    return sorted(set(rel))


def load_baseline(path: Optional[str] = None) -> List[dict]:
    """Baseline entries (list of dicts). Every entry MUST have fingerprint +
    justification; malformed entries raise — a baseline that cannot explain
    itself is worse than none."""
    path = path or DEFAULT_BASELINE_PATH
    try:
        with open(path) as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return []
    entries = data.get("entries", []) if isinstance(data, dict) else data
    for e in entries:
        if not e.get("fingerprint") or not str(e.get("justification", "")).strip():
            raise ValueError(
                f"baseline entry {e!r} in {path} lacks a fingerprint or a "
                f"justification — every baselined finding must say why"
            )
    return entries


def write_baseline(entries: List[dict], path: Optional[str] = None) -> None:
    path = path or DEFAULT_BASELINE_PATH
    with open(path, "w") as fh:
        json.dump({"entries": entries}, fh, indent=2)
        fh.write("\n")


def prune_baseline(report: "Report", baseline: List[dict],
                   path: Optional[str] = None) -> int:
    """Drop the baseline entries ``report`` found stale (their fingerprint
    matched no finding) and rewrite the baseline file. Returns the number of
    entries removed. The surviving entries keep their order and their
    human-written justifications untouched."""
    stale_fps = {e.get("fingerprint") for e in report.stale_baseline}
    if not stale_fps:
        return 0
    kept = [e for e in baseline if e.get("fingerprint") not in stale_fps]
    write_baseline(kept, path)
    return len(baseline) - len(kept)


def changed_files(root: Optional[str] = None, base: str = "HEAD") -> List[str]:
    """Python files changed relative to ``base`` (``git diff`` against the
    merge base) plus untracked ones — the ``--changed-only`` working set.
    Raises RuntimeError when git is unusable: a pre-commit gate that cannot
    see the diff must fail loudly, not pass on an empty file list."""
    import subprocess

    root = os.path.abspath(root or os.getcwd())

    def git(*args: str) -> str:
        proc = subprocess.run(
            ["git", "-C", root, *args],
            capture_output=True, text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(args)} failed: {proc.stderr.strip()}"
            )
        return proc.stdout

    merge_base = git("merge-base", "HEAD", base).strip() if base != "HEAD" \
        else "HEAD"
    # `git diff --name-only` emits repo-TOPLEVEL-relative paths while
    # `ls-files --others` emits cwd-relative ones — normalize both against
    # the toplevel, then re-relativize to ``root`` so a run from a repo
    # subdirectory still sees every changed tracked file (a silent drop
    # here is exactly the empty-file-list pass this function must prevent)
    top = git("rev-parse", "--show-toplevel").strip()
    out = git("diff", "--name-only", "-z", merge_base, "--")
    out += git("ls-files", "--others", "--exclude-standard", "--full-name",
               "-z")
    files = set()
    for f in out.split("\0"):
        if not f.endswith(".py"):
            continue
        ap = os.path.join(top, f)
        if not os.path.isfile(ap):
            continue
        rp = os.path.relpath(ap, root)
        if not rp.startswith(".."):
            files.add(rp)
    return sorted(files)


def _run_rules(mod: SourceModule, rules,
               rule_times: Optional[dict] = None) -> List[tuple]:
    """[(finding, node)] for one module, rule errors converted to findings
    (an analyzer crash must be visible, not a silent pass). ``rule_times``
    accumulates per-rule wall seconds across modules; a rule that lazily
    builds a shared index (the concurrency index under JG024) is charged
    for that build on its first run — the honest attribution."""
    out = []
    for rule in rules:
        if mod.is_test and getattr(rule, "skip_tests", False):
            continue
        t0 = time.perf_counter()
        try:
            for item in rule.check(mod):
                if isinstance(item, tuple):
                    out.append(item)
                else:
                    out.append((item, None))
        except Exception as exc:  # pragma: no cover - rule bug guard
            out.append((
                Finding(
                    code="JG000",
                    message=(
                        f"rule {rule.code} crashed on this file: "
                        f"{type(exc).__name__}: {exc}"
                    ),
                    path=mod.path,
                    line=1,
                    col=0,
                    snippet="",
                ),
                None,
            ))
        if rule_times is not None:
            rule_times[rule.code] = (rule_times.get(rule.code, 0.0)
                                     + time.perf_counter() - t0)
    return out


def analyze_modules(mods, rules=None, baseline=None,
                    check_paths=None, cache_stats=None) -> Report:
    """Two-phase analysis: materialize every module, build the project
    index (phase 1), then run the rules (phase 2). Cross-module rules may
    attribute a finding to a DIFFERENT file than the one being iterated
    (e.g. a scan body defined a module away) — suppression is therefore
    checked against the module that owns the finding's path.

    ``check_paths`` (a set, or None for all) restricts phase 2 to those
    modules while phase 1 still indexes everything — the ``--changed-only``
    shape: full cross-module context, rules paid only for the changed
    files. ``cache_stats`` is a ``{"hits": .., "misses": ..}`` dict from a
    :class:`ParseCache`, surfaced in the profile."""
    from gan_deeplearning4j_tpu.analysis import project as _project
    from gan_deeplearning4j_tpu.analysis.rules import RULES, RULES_BY_CODE

    rules = RULES if rules is None else rules
    baseline = baseline or []
    by_fp = {e["fingerprint"]: e for e in baseline}
    matched_fps = set()
    migrations: dict = {}
    active, suppressed, baselined = [], [], []
    warnings: List[str] = []
    seen = set()  # scope overlap can surface one defect twice — keep first
    t0 = time.perf_counter()
    mods = list(mods)  # consuming the generator = reading + parsing
    t_parse = time.perf_counter() - t0
    parsed = [m for m in mods if isinstance(m, SourceModule)]
    t0 = time.perf_counter()
    index = _project.build_index(parsed)
    t_index = time.perf_counter() - t0
    mod_by_path = {}
    for m in parsed:
        m.project = index
        mod_by_path[m.path] = m
    checked = [m for m in mods
               if check_paths is None
               or getattr(m, "path", None) in check_paths]
    known_codes = set(RULES_BY_CODE) | {"all", "JG000"}
    for m in checked:
        if not isinstance(m, SourceModule):
            continue
        for line, codes in sorted(m.suppressions.items()):
            for code in sorted(codes - known_codes):
                warnings.append(
                    f"{m.path}:{line}: suppression names unknown rule code "
                    f"{code!r} — it suppresses nothing; check for a typo"
                )
    files = 0
    rule_times: dict = {}
    t0 = time.perf_counter()
    for mod in checked:
        files += 1
        if isinstance(mod, Finding):  # parse failure
            active.append(mod)
            continue
        for finding, node in _run_rules(mod, rules, rule_times):
            key = (finding.code, finding.path, finding.line, finding.col)
            if key in seen:
                continue
            seen.add(key)
            owner = mod_by_path.get(finding.path, mod)
            if owner.suppressed(finding, node):
                suppressed.append(finding)
            elif finding.fingerprint in by_fp:
                matched_fps.add(finding.fingerprint)
                baselined.append(finding)
            elif finding.legacy_fingerprint in by_fp:
                # pre-context-scheme entry: still honored, and recorded
                # for auto-migration to the current fingerprint
                matched_fps.add(finding.legacy_fingerprint)
                migrations[finding.legacy_fingerprint] = finding.fingerprint
                baselined.append(finding)
            else:
                active.append(finding)
    # Staleness is judged ONLY within this run's scope: an entry whose path
    # was not analyzed (or not rule-checked — --changed-only indexes the
    # full tree but checks a subset) or whose rule did not run might still
    # match on the next full run — calling it stale here would fail every
    # scoped run (--changed-only, path subsets, --rules) and let
    # --prune-baseline delete still-valid entries. Entries without
    # path/rule metadata are conservatively treated as in-scope.
    analyzed = {m.path for m in checked if hasattr(m, "path")}
    rule_codes = {r.code for r in rules}
    stale = [
        e for e in baseline
        if e["fingerprint"] not in matched_fps
        and (not e.get("path") or e["path"] in analyzed)
        and (not e.get("rule") or e["rule"] in rule_codes)
    ]
    t_rules = time.perf_counter() - t0
    # Deterministic emission order for EVERY partition, not just active:
    # findings surface in module-iteration order, which depends on how the
    # caller enumerated paths — two runs over the same tree must render
    # byte-identical text/JSON/SARIF regardless.
    order = lambda f: (f.path, f.line, f.code)  # noqa: E731
    active.sort(key=order)
    suppressed.sort(key=order)
    baselined.sort(key=order)
    warnings.sort()
    stale.sort(key=lambda e: (e.get("path") or "", e["fingerprint"]))
    profile = {
        "phases": {"parse": t_parse, "index": t_index, "rules": t_rules},
        "rules": rule_times,
    }
    if cache_stats is not None:
        profile["cache"] = dict(cache_stats)
    return Report(active, suppressed, baselined, stale, files,
                  warnings=warnings, profile=profile,
                  baseline_migrations=migrations, index=index)


class ParseCache:
    """Per-file persistence of parsed modules, keyed by content hash.

    One pickle per file under ``dirpath``, named by
    ``sha256(version|relpath|content)`` — an edited file simply misses
    (its old entry is overwritten on store, so the directory does not
    grow per edit), and any unpicklable/corrupt entry degrades to a miss.
    Only the parse phase is cached; rules always re-run (findings are
    cross-module facts). ``stats`` feeds the ``--profile`` table."""

    def __init__(self, dirpath: str) -> None:
        self.dir = dirpath
        self.stats = {"hits": 0, "misses": 0}
        os.makedirs(dirpath, exist_ok=True)

    def _key(self, relpath: str, text: str) -> str:
        norm = relpath.replace(os.sep, "/")
        return hashlib.sha256(
            f"{CACHE_VERSION}|{norm}\0{text}".encode()
        ).hexdigest()

    def _entry(self, relpath: str) -> str:
        # stable per-PATH filename (content hash verified inside): an
        # edit REPLACES the file's entry instead of accreting stale blobs
        name = hashlib.sha256(
            relpath.replace(os.sep, "/").encode()).hexdigest()
        return os.path.join(self.dir, f"{name}.pkl")

    def load(self, relpath: str, text: str):
        """Cached parse_module() result (SourceModule or Finding), or
        None on miss."""
        try:
            with open(self._entry(relpath), "rb") as fh:
                key, obj = pickle.load(fh)
        except Exception:
            self.stats["misses"] += 1
            return None
        if key != self._key(relpath, text):
            self.stats["misses"] += 1
            return None
        self.stats["hits"] += 1
        return obj

    def store(self, relpath: str, text: str, obj) -> None:
        """Best-effort write (a read-only cache dir must not fail the
        lint run); ``obj.project`` is never persisted."""
        if isinstance(obj, SourceModule):
            obj = dataclasses.replace(obj, project=None)
        try:
            tmp = self._entry(relpath) + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                pickle.dump((self._key(relpath, text), obj), fh,
                            protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self._entry(relpath))
        except Exception:
            pass


def analyze_paths(paths, rules=None, baseline=None, root=None,
                  cache: Optional[ParseCache] = None,
                  check_paths=None) -> Report:
    """Analyze files/directories. ``baseline`` is a loaded entry list (use
    :func:`load_baseline`), or None for no baseline. ``cache`` short-cuts
    the parse phase for unchanged files; ``check_paths`` restricts the
    rule phase (phase 1 still indexes every collected file)."""
    root = os.path.abspath(root or os.getcwd())

    def gen():
        for rp in collect_files(paths, root):
            ap = rp if os.path.isabs(rp) else os.path.join(root, rp)
            try:
                with open(ap, encoding="utf-8", errors="replace") as fh:
                    text = fh.read()
            except OSError as exc:
                yield Finding("JG000", f"unreadable: {exc}", rp, 1, 0, "")
                continue
            if cache is not None:
                hit = cache.load(rp, text)
                if hit is not None:
                    yield hit
                    continue
            mod = parse_module(text, rp)
            if cache is not None:
                cache.store(rp, text, mod)
            yield mod

    return analyze_modules(
        gen(), rules=rules, baseline=baseline, check_paths=check_paths,
        cache_stats=None if cache is None else cache.stats)


def analyze_source(text: str, path: str = "<string>", rules=None,
                   baseline=None, is_test=None) -> Report:
    """Analyze one in-memory module — the fixture entry point for tests.
    ``is_test=None`` derives test-ness from ``path`` like the file walker."""
    mod = parse_module(text, path, is_test=is_test)
    return analyze_modules([mod], rules=rules, baseline=baseline)


def analyze_sources(sources: dict, rules=None, baseline=None) -> Report:
    """Analyze several in-memory modules TOGETHER (one project index) —
    the fixture entry point for cross-module rules. ``sources`` maps
    engine-relative paths to module text; paths determine module names
    (``pkg/mod.py`` -> ``pkg.mod``), so imports between the sources
    resolve exactly as they would on disk."""
    mods = [parse_module(text, path) for path, text in sorted(sources.items())]
    return analyze_modules(mods, rules=rules, baseline=baseline)
