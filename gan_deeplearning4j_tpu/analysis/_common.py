"""Shared AST plumbing for jaxlint rules (stdlib-only).

The one piece of real machinery here is import-alias resolution: rules match
on *resolved* dotted names (``jax.random.uniform``), not surface spellings,
so ``import jax.random as jr; jr.uniform(...)`` and
``from jax import random; random.uniform(...)`` both hit — while the
stdlib's ``random.uniform`` in a module that never imports jax does not.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

# -- shared name sets (single source of truth for every rule) ---------------

#: callables whose function argument is traced (jit/grad/vmap/shard_map and
#: the lax control-flow combinators)
TRACING_WRAPPERS = {
    "jax.jit", "jax.pmap", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.jacfwd", "jax.jacrev", "jax.hessian", "jax.checkpoint", "jax.remat",
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.custom_jvp", "jax.custom_vjp",
}

#: wall-clock reads that mark a region as "timed"
CLOCK_CALLS = {
    "time.perf_counter", "time.perf_counter_ns", "time.monotonic",
    "time.monotonic_ns", "time.time", "timeit.default_timer",
}

#: host-callback escapes out of traced code — each one serializes the device
#: pipeline through the host when it runs
HOST_CALLBACKS = {
    "jax.pure_callback", "jax.experimental.pure_callback",
    "jax.experimental.io_callback", "jax.experimental.io_callback.io_callback",
    "jax.debug.callback", "jax.debug.print",
}

#: jit-like transforms that accept donate_argnums
JIT_WRAPPERS = {"jax.jit", "jax.pmap"}

#: synchronous host I/O — file writes/fsyncs and blocking network calls.
#: On the step path every one of these idles the accelerator while the
#: host blocks (the measured checkpoint-write stall: 34% of wall on the
#: toy workload, BENCH_resilience_r01.json); JG020 flags them when a
#: timed train-step region reaches one through the call graph.
SYNC_IO_CALLS = {
    "open", "io.open", "os.fsync", "os.fdatasync", "os.write",
    "os.replace", "os.rename",
    "shutil.copy", "shutil.copy2", "shutil.copyfile", "shutil.copytree",
    "urllib.request.urlopen",
    "socket.socket", "socket.create_connection",
    "http.client.HTTPConnection", "http.client.HTTPSConnection",
}

#: process-spawning entry points — each one launches an OS process. A
#: supervision loop that reaches one of these with neither an attempt cap
#: nor a backoff sleep on its failure path is a fork bomb with extra
#: steps; JG021 flags the loop (the fleet manager's spawn-failure backoff
#: is the corrected idiom).
SPAWN_CALLS = {
    "subprocess.Popen", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "os.fork", "os.posix_spawn", "os.spawnv", "os.spawnl",
    "multiprocessing.Process",
}

#: direct backoff-sleep shapes a respawn loop may pace itself with
SLEEP_CALLS = {"time.sleep"}


def build_import_map(tree: ast.AST) -> dict:
    """Local name -> fully-qualified dotted prefix, from import statements.

    ``import jax`` -> {"jax": "jax"}; ``import jax.random as jr`` ->
    {"jr": "jax.random"}; ``from jax import random`` ->
    {"random": "jax.random"}; ``from jax.random import split as sp`` ->
    {"sp": "jax.random.split"}. Relative imports map into a ``.``-prefixed
    pseudo-root so they never collide with real top-level packages.
    """
    mapping: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    mapping[alias.asname] = alias.name
                else:
                    # "import jax.random" binds the name "jax"
                    first = alias.name.split(".")[0]
                    mapping[first] = first
        elif isinstance(node, ast.ImportFrom):
            base = ("." * node.level) + (node.module or "")
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                mapping[local] = f"{base}.{alias.name}" if base else alias.name
    return mapping


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_name(node: ast.AST, imports: dict) -> Optional[str]:
    """Resolved dotted name of an expression through the import map."""
    dotted = dotted_name(node)
    if dotted is None:
        return None
    first, _, rest = dotted.partition(".")
    root = imports.get(first)
    if root is None:
        return dotted
    return f"{root}.{rest}" if rest else root


def resolve_call(call: ast.Call, imports: dict) -> Optional[str]:
    return resolve_name(call.func, imports)


def base_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name id of an expression (``ks[0].foo`` -> ``ks``)."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def loaded_names(node: ast.AST) -> set:
    """All Name ids read anywhere inside an expression."""
    return {
        n.id
        for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _target_names(target: ast.AST, out: set) -> None:
    if isinstance(target, ast.Name):
        out.add(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            _target_names(elt, out)
    elif isinstance(target, ast.Starred):
        _target_names(target.value, out)
    # Attribute/Subscript targets mutate an object, they don't bind a name


def bound_names(node: ast.AST) -> set:
    """Every name BOUND anywhere under ``node``: assignments (incl. walrus,
    aug/ann-assign), for targets, with-as, def/class statements, imports,
    except-as. Used for "was this rebound inside the loop/body?" checks."""
    out: set = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                _target_names(t, out)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            _target_names(n.target, out)
        elif isinstance(n, ast.NamedExpr):
            _target_names(n.target, out)
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            _target_names(n.target, out)
        elif isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if item.optional_vars is not None:
                    _target_names(item.optional_vars, out)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            out.add(n.name)
        elif isinstance(n, (ast.Import, ast.ImportFrom)):
            for alias in n.names:
                out.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(n, ast.ExceptHandler) and n.name:
            out.add(n.name)
    return out


def assignment_targets(stmt: ast.stmt) -> set:
    """Names bound by THIS statement's own targets (not descendants)."""
    out: set = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            _target_names(t, out)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        _target_names(stmt.target, out)
    return out


def walk_excluding_defs(roots) -> Iterator[ast.AST]:
    """Walk node(s) without descending into nested function/lambda bodies —
    their execution is deferred, so they are not part of the enclosing
    statement/loop's own evaluation (and defs are separate rule scopes)."""
    stack = list(roots) if isinstance(roots, (list, tuple)) else [roots]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def iter_scopes(tree: ast.AST) -> Iterator[ast.AST]:
    """The module plus every (async) function def, outermost first."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def iter_loops(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            yield node


def call_args_with_keywords(call: ast.Call) -> Iterator:
    """(position_or_name, value_node) for every argument of a call."""
    for i, arg in enumerate(call.args):
        yield i, arg
    for kw in call.keywords:
        if kw.arg is not None:
            yield kw.arg, kw.value


def literal_int_tuple(node: ast.AST):
    """Value of an int / tuple-or-list-of-ints literal, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)):
                return None
            vals.append(elt.value)
        return tuple(vals)
    return None
