"""jaxlint — in-tree static analysis for JAX/TPU training hazards.

Rounds 3-5 lost on-chip evidence to bug classes that are mechanically
detectable at the AST level: a timing harness fencing on a stale output
(round 5, ``scripts/mfu_ceiling.py``), protocol guards written as bare
``assert`` (stripped under ``python -O``), and PRNG/jit hygiene that only a
human reviewer audited. This package turns those review rules into code.

v2 is a TWO-PHASE analyzer. Phase 1 (:mod:`.project`) builds a project
index — module/import graph, top-level symbol table, per-function summaries
(donated parameters, PRNG-key parameters, traced-ness through decorator
chains and ``functools.partial``, host-callback taint) — and phase 2 runs
the rules with that index on every module, so donation misuse through
``functools.partial``/import indirection, callbacks reached from timed
regions, and axis arities of functions defined a module away are all
visible (JG007–JG011 join PR 1's JG001–JG006). A lazily-built
**concurrency index** (:mod:`.concurrency`: thread entry points,
per-method attribute accesses with held-lock sets, lock-acquisition
sequences) extends phase 1 for the thread-safety rules JG024–JG026.

Deliberately jax-free and stdlib-only: the analyzer must run on the parent
side of the bench architecture (bench.py's parent never imports jax — a dead
chip can hang ``import jax`` for minutes) and in any CI container regardless
of which accelerator stack is installed.

Public surface:

- :func:`analyze_paths` / :func:`analyze_source` / :func:`analyze_sources`
  — run all rules, return :class:`Report` (findings partitioned into
  active / suppressed / baselined; ``analyze_sources`` analyzes several
  in-memory modules under ONE project index — the cross-module fixture
  entry point).
- :class:`Finding` — one diagnostic, with a content-based fingerprint that
  is stable across line-number drift (rule code + path + normalized source
  line), so baselines survive unrelated edits.
- :data:`RULES` — the rule registry (JG001-JG011; see
  ``docs/STATIC_ANALYSIS.md`` for the catalogue and the real bug behind
  each rule).
- CLI: ``python -m gan_deeplearning4j_tpu.analysis <paths>`` — exit 0 iff
  the tree is clean modulo the checked-in baseline
  (``analysis/_baseline.json``). ``--format sarif`` for CI annotators,
  ``--changed-only`` for the pre-commit fast path
  (``scripts/lint_gate.sh``), ``--fix``/``--fix-suppress`` for the
  mechanical-rewrite subset, ``--prune-baseline`` for baseline hygiene.
  A tier-1 test (``tests/test_analysis.py::TestTreeIsClean``) holds the
  clean-tree invariant, including over the analyzer's own package.

Suppression: a trailing ``# jaxlint: disable=JG001`` (comma-separated codes,
or ``all``) on any line of the offending statement suppresses the finding;
suppressions are counted and reported, never silent, and a suppression
naming an unknown rule code is a reported warning, not a silent no-op.
"""

from gan_deeplearning4j_tpu.analysis.engine import (
    DEFAULT_BASELINE_PATH,
    Finding,
    Report,
    analyze_paths,
    analyze_source,
    analyze_sources,
    changed_files,
    load_baseline,
    prune_baseline,
)
from gan_deeplearning4j_tpu.analysis.rules import RULES

__all__ = [
    "DEFAULT_BASELINE_PATH",
    "Finding",
    "Report",
    "RULES",
    "analyze_paths",
    "analyze_source",
    "analyze_sources",
    "changed_files",
    "load_baseline",
    "prune_baseline",
]
