"""jaxlint CLI.

    python -m gan_deeplearning4j_tpu.analysis gan_deeplearning4j_tpu bench.py scripts

Exit codes: 0 clean (modulo baseline + suppressions), 1 active findings or
stale baseline entries, 2 usage error. ``--format json`` emits one machine-
readable object and ``--format sarif`` emits SARIF 2.1.0 for CI annotators;
default text output is one ``path:line:col: CODE message`` row per finding —
the same shape compiler diagnostics use, so editors annotate it for free.

Modes beyond plain analysis:

- ``--changed-only [--diff-base REF]`` runs rules only on .py files
  changed vs the merge base with REF (plus untracked files) — the fast
  pre-commit shape ``scripts/lint_gate.sh`` wraps. The full target set
  is still parsed and indexed (cross-module rules need real context);
  with ``--cache-dir`` that parse is warm, so the run costs roughly
  rules-on-the-diff;
- ``--cache-dir DIR`` persists parsed modules keyed by content hash
  (``LINT_CACHE=off`` is the escape hatch; hit/miss counts under
  ``--profile``);
- ``--fix`` applies the mechanical rewrites (JG003 asserts, JG007
  discarded updates) and re-reports what remains; ``--fix-suppress``
  instead inserts per-line suppressions for every remaining active
  finding and REQUIRES ``--justification``;
- ``--prune-baseline`` rewrites the baseline file dropping entries whose
  fingerprint no longer matches any finding (stale entries otherwise FAIL
  the gate — a fixed bug must leave the baseline, not haunt it);
- ``--write-baseline`` snapshots the CURRENT active findings into the
  baseline file with a placeholder justification that the loader will
  refuse until a human edits it — regenerating a baseline is deliberately
  a two-step act.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from gan_deeplearning4j_tpu.analysis import engine
from gan_deeplearning4j_tpu.analysis.rules import RULES


def _render_profile(report, rules) -> str:
    """Wall-time table for --profile: phases first, then every rule that
    ran, slowest first. Times are wall seconds of this run; the phase-1
    indexes built lazily by a rule (the concurrency index under JG024)
    are charged to the rule that triggered the build."""
    prof = report.profile or {"phases": {}, "rules": {}}
    names = {r.code: r.name for r in rules}
    lines = ["# jaxlint --profile (wall seconds)"]
    phases = prof.get("phases", {})
    for key in ("parse", "index", "rules"):
        if key in phases:
            lines.append(f"#   phase {key:<8s} {phases[key]:8.3f}s")
    cache = prof.get("cache")
    if cache is not None:
        lines.append(f"#   cache hits {cache.get('hits', 0)} / "
                     f"misses {cache.get('misses', 0)}")
    per_rule = prof.get("rules", {})
    for code in sorted(per_rule, key=lambda c: (-per_rule[c], c)):
        lines.append(f"#   {code} {names.get(code, '?'):<34s} "
                     f"{per_rule[code]:8.3f}s")
    return "\n".join(lines)


def _emit(report, fmt: str, rules, baseline) -> None:
    if fmt == "json":
        print(json.dumps(report.to_json(), indent=2))
    elif fmt == "sarif":
        from gan_deeplearning4j_tpu.analysis import sarif

        print(json.dumps(sarif.to_sarif(report, rules, baseline), indent=2))
    else:
        print(report.render_text())


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gan_deeplearning4j_tpu.analysis",
        description="jaxlint: static analysis for JAX/TPU training hazards",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to analyze")
    p.add_argument("--format", choices=["text", "json", "sarif"],
                   default="text")
    p.add_argument("--baseline", default=engine.DEFAULT_BASELINE_PATH,
                   help="baseline file (default: the checked-in "
                        "analysis/_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="snapshot current active findings into --baseline "
                        "with TODO justifications (edit before committing)")
    p.add_argument("--prune-baseline", action="store_true",
                   help="rewrite --baseline without entries whose "
                        "fingerprint matches no current finding")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--changed-only", action="store_true",
                   help="only analyze .py files changed vs --diff-base "
                        "(merge base) plus untracked files")
    p.add_argument("--diff-base", default="HEAD",
                   help="git ref --changed-only diffs against via the merge "
                        "base (default: HEAD = uncommitted changes only)")
    p.add_argument("--fix", action="store_true",
                   help="apply mechanical rewrites for fixable findings "
                        "(JG003, JG007), then re-report")
    p.add_argument("--fix-suppress", action="store_true",
                   help="insert justified per-line suppressions for every "
                        "remaining active finding (requires --justification)")
    p.add_argument("--justification", default=None,
                   help="human reason recorded by --fix-suppress")
    p.add_argument("--profile", action="store_true",
                   help="print a per-phase/per-rule wall-time table to "
                        "stderr (the report itself is unchanged)")
    p.add_argument("--cache-dir", default=os.environ.get("JAXLINT_CACHE_DIR"),
                   help="persist parsed modules here keyed by content hash "
                        "so repeat runs skip the parse phase for unchanged "
                        "files (default: $JAXLINT_CACHE_DIR, else no cache; "
                        "LINT_CACHE=off disables even an explicit dir)")
    p.add_argument("--lifecycle-stats", default=None, metavar="FILE",
                   help="write lifecycle-index stats (pairs discovered, "
                        "opens, transfers resolved) as JSON to FILE — the "
                        "campaign preflight snapshot")
    args = p.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            print(f"{r.code}  {r.name}: {r.summary}")
        return 0
    if not args.paths:
        p.error("no paths given")
    if args.fix_suppress and not (args.justification or "").strip():
        p.error("--fix-suppress requires --justification (a suppression "
                "must say why)")

    rules = RULES
    if args.rules:
        wanted = {c.strip().upper() for c in args.rules.split(",")}
        rules = [r for r in RULES if r.code in wanted]
        unknown = wanted - {r.code for r in rules}
        if unknown:
            p.error(f"unknown rule codes: {sorted(unknown)}")

    try:
        baseline = None if args.no_baseline else engine.load_baseline(args.baseline)
    except ValueError as exc:
        print(f"jaxlint: {exc}", file=sys.stderr)
        return 2

    try:
        targets = engine.collect_files(args.paths)
    except FileNotFoundError as exc:
        print(f"jaxlint: {exc}", file=sys.stderr)
        return 2

    # --changed-only still PARSES every target (phase 1 indexes the full
    # tree, so cross-module rules see real context — the cache makes that
    # cheap) but runs rules only on the changed files
    check_paths = None
    if args.changed_only:
        try:
            changed = set(engine.changed_files(base=args.diff_base))
        except RuntimeError as exc:
            print(f"jaxlint: --changed-only needs a usable git checkout: "
                  f"{exc}", file=sys.stderr)
            return 2
        check_paths = {t for t in targets if t in changed}
        if not check_paths:
            print("# jaxlint: no changed .py files under the given paths",
                  file=sys.stderr)
            return 0

    cache = None
    if args.cache_dir and os.environ.get("LINT_CACHE", "").lower() != "off":
        try:
            cache = engine.ParseCache(args.cache_dir)
        except OSError as exc:
            print(f"jaxlint: cache disabled ({exc})", file=sys.stderr)

    def run():
        return engine.analyze_paths(targets, rules=rules, baseline=baseline,
                                    cache=cache, check_paths=check_paths)

    report = run()

    if report.baseline_migrations and not args.no_baseline:
        # entries matched under the legacy fingerprint scheme: rewrite
        # them in place so the next run matches directly
        entries = engine.load_baseline(args.baseline)
        moved = 0
        for e in entries:
            new_fp = report.baseline_migrations.get(e.get("fingerprint"))
            if new_fp is not None:
                e["fingerprint"] = new_fp
                moved += 1
        if moved:
            engine.write_baseline(entries, args.baseline)
            print(f"jaxlint: migrated {moved} baseline "
                  f"entr{'y' if moved == 1 else 'ies'} to context-aware "
                  f"fingerprints in {args.baseline}", file=sys.stderr)
            baseline = engine.load_baseline(args.baseline)

    if args.lifecycle_stats and report.index is not None:
        with open(args.lifecycle_stats, "w") as fh:
            json.dump(report.index.lifecycle.stats(), fh, indent=2,
                      sort_keys=True)
            fh.write("\n")

    if args.write_baseline:
        entries = [
            {
                "fingerprint": f.fingerprint,
                "rule": f.code,
                "path": f.path,
                "line": f.line,
                "snippet": f.snippet,
                "justification": "TODO: justify or fix",
            }
            for f in report.active
        ]
        engine.write_baseline(entries, args.baseline)
        print(f"jaxlint: wrote {len(entries)} entries to {args.baseline} — "
              f"replace every TODO justification before committing",
              file=sys.stderr)
        return 0

    if args.prune_baseline:
        removed = engine.prune_baseline(report, baseline or [], args.baseline)
        print(f"jaxlint: pruned {removed} stale baseline "
              f"entr{'y' if removed == 1 else 'ies'} from {args.baseline}",
              file=sys.stderr)
        baseline = engine.load_baseline(args.baseline)
        report = run()

    if args.fix or args.fix_suppress:
        from gan_deeplearning4j_tpu.analysis import fix as _fix

        result = _fix.apply_fixes(
            report,
            suppress=args.fix_suppress,
            justification=args.justification,
        )
        print(
            f"jaxlint: rewrote {result.rewritten}, suppressed "
            f"{result.suppressed} finding(s) in {len(result.files)} file(s)",
            file=sys.stderr,
        )
        for s in result.skipped:
            print(f"jaxlint: not mechanically fixable: {s}", file=sys.stderr)
        report = run()  # re-analyze: the output reflects the tree on disk

    if args.profile:
        print(_render_profile(report, rules), file=sys.stderr)
    _emit(report, args.format, rules, baseline)
    return 0 if report.gate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
