"""jaxlint CLI.

    python -m gan_deeplearning4j_tpu.analysis gan_deeplearning4j_tpu bench.py scripts

Exit codes: 0 clean (modulo baseline + suppressions), 1 active findings or
stale baseline entries, 2 usage error. ``--format json`` emits one machine-
readable object; default text output is one ``path:line:col: CODE message``
row per finding — the same shape compiler diagnostics use, so editors and CI
annotate it for free.

``--write-baseline`` snapshots the CURRENT active findings into the baseline
file with a placeholder justification that the loader will refuse until a
human edits it — regenerating a baseline is deliberately a two-step act.
"""

from __future__ import annotations

import argparse
import json
import sys

from gan_deeplearning4j_tpu.analysis import engine
from gan_deeplearning4j_tpu.analysis.rules import RULES


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gan_deeplearning4j_tpu.analysis",
        description="jaxlint: static analysis for JAX/TPU training hazards",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to analyze")
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument("--baseline", default=engine.DEFAULT_BASELINE_PATH,
                   help="baseline file (default: the checked-in "
                        "analysis/_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="snapshot current active findings into --baseline "
                        "with TODO justifications (edit before committing)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            print(f"{r.code}  {r.name}: {r.summary}")
        return 0
    if not args.paths:
        p.error("no paths given")

    rules = RULES
    if args.rules:
        wanted = {c.strip().upper() for c in args.rules.split(",")}
        rules = [r for r in RULES if r.code in wanted]
        unknown = wanted - {r.code for r in rules}
        if unknown:
            p.error(f"unknown rule codes: {sorted(unknown)}")

    try:
        baseline = None if args.no_baseline else engine.load_baseline(args.baseline)
    except ValueError as exc:
        print(f"jaxlint: {exc}", file=sys.stderr)
        return 2

    try:
        report = engine.analyze_paths(args.paths, rules=rules, baseline=baseline)
    except FileNotFoundError as exc:
        print(f"jaxlint: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        entries = [
            {
                "fingerprint": f.fingerprint,
                "rule": f.code,
                "path": f.path,
                "line": f.line,
                "snippet": f.snippet,
                "justification": "TODO: justify or fix",
            }
            for f in report.active
        ]
        with open(args.baseline, "w") as fh:
            json.dump({"entries": entries}, fh, indent=2)
            fh.write("\n")
        print(f"jaxlint: wrote {len(entries)} entries to {args.baseline} — "
              f"replace every TODO justification before committing",
              file=sys.stderr)
        return 0

    if args.format == "json":
        print(json.dumps(report.to_json(), indent=2))
    else:
        print(report.render_text())
    return 0 if report.clean and not report.stale_baseline else 1


if __name__ == "__main__":
    sys.exit(main())
