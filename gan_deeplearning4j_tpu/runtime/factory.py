"""Array factory — the ``Nd4j`` static-factory surface, device-resident.

TPU-native equivalent of the ND4J factory ops the reference exercises
(``Nd4j.randn/rand/create/linspace/ones/zeros/vstack`` and in-place
``muli/subi/addi``/``reshape``, dl4jGANComputerVision.java:105,170,382-388,
404-406,420,465,551-552). Arrays are ordinary ``jax.Array``s living in device
HBM (via PJRT under the hood); "in-place" ND4J mutation becomes functional
updates, which XLA turns into buffer reuse/donation.

All factories honor the global dtype policy (runtime.dtype) and take explicit
PRNG keys (or an :class:`RngStream`) instead of ND4J's hidden global RNG.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from gan_deeplearning4j_tpu.runtime.dtype import get_default_dtype
from gan_deeplearning4j_tpu.runtime.prng import RngStream


def _resolve_key(rng):
    if isinstance(rng, RngStream):
        return rng.next_key()
    return rng


def _dtype(dtype):
    return get_default_dtype() if dtype is None else jnp.dtype(dtype)


def randn(rng, *shape, dtype=None):
    """Standard-normal samples (Nd4j.randn analog)."""
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return jax.random.normal(_resolve_key(rng), shape, dtype=_dtype(dtype))


def rand(rng, *shape, dtype=None, minval=0.0, maxval=1.0):
    """Uniform samples in [minval, maxval) (Nd4j.rand analog)."""
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return jax.random.uniform(
        _resolve_key(rng), shape, dtype=_dtype(dtype), minval=minval, maxval=maxval
    )


def uniform_latent(rng, *shape, dtype=None):
    """z ~ U(-1, 1) — the reference's latent sampler ``rand·2−1``
    (dl4jGANComputerVision.java:420,465)."""
    return rand(rng, *shape, dtype=dtype, minval=-1.0, maxval=1.0)


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=_dtype(dtype))


def ones(*shape, dtype=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return jnp.ones(shape, dtype=_dtype(dtype))


def zeros(*shape, dtype=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return jnp.zeros(shape, dtype=_dtype(dtype))


def create(data, dtype=None):
    """Materialize host data as a device array (Nd4j.create analog)."""
    return jnp.asarray(np.asarray(data), dtype=_dtype(dtype))


def vstack(arrays: Sequence[jax.Array]):
    """Row-stack (Nd4j.vstack analog, dl4jGANComputerVision.java:551,581)."""
    return jnp.concatenate([jnp.atleast_2d(a) for a in arrays], axis=0)


def latent_grid(side: int, low: float = -1.0, high: float = 1.0, dtype=None):
    """The reference's z-grid for latent-manifold plots: a ``side × side``
    cartesian grid over ``linspace(low, high, side)²`` flattened to
    ``(side², 2)`` (dl4jGANComputerVision.java:382-389)."""
    axis = jnp.linspace(low, high, side, dtype=_dtype(dtype))
    xx, yy = jnp.meshgrid(axis, axis, indexing="ij")
    return jnp.stack([xx.reshape(-1), yy.reshape(-1)], axis=-1)


def to_host(array) -> np.ndarray:
    """Explicit device→host transfer. The only sanctioned host readout point —
    the reference's per-scalar ``getDouble`` reads
    (dl4jGANComputerVision.java:558,587) are deliberately not reproduced; batch
    reads through this instead."""
    return np.asarray(jax.device_get(array))
