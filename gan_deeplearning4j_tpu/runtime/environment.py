"""Device environment & runtime configuration.

TPU-native replacement for ``CudaEnvironment.getInstance().getConfiguration()``
(reference: dl4jGANComputerVision.java:107-111) and the backend identification
print (``Nd4j.getBackend()``, :114). Where the reference configures a CUDA
JITA allocator (multi-GPU, 2 GiB device cache, P2P cross-device access), the
TPU runtime's analogs are: PJRT owns HBM allocation, ICI provides cross-device
access natively, and multi-device execution is expressed through a
``jax.sharding.Mesh`` rather than toggled on.

``TpuEnvironment`` therefore carries the knobs that *do* exist on this stack:
platform selection, visible device count, mesh axis layout, verbosity, and the
memory-pressure escape hatches XLA exposes (rematerialization policy, donation).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

logger = logging.getLogger(__name__)


def backend_info() -> dict:
    """Identify the execution backend (analog of ``Nd4j.getBackend()`` print,
    dl4jGANComputerVision.java:114)."""
    devices = jax.devices()
    return {
        "platform": devices[0].platform if devices else jax.default_backend(),
        "device_count": len(devices),
        "local_device_count": jax.local_device_count(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "devices": [str(d) for d in devices],
    }


_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_cache_enabled: Optional[str] = None  # the active cache dir, once applied


def _host_cache_tag() -> str:
    """Per-host cache-compatibility tag (round-3 VERDICT weak #2).

    XLA:CPU cache entries embed AOT machine code specialized to the
    *compiling* host's CPU features; jax loads them on a host with different
    features anyway ("could lead to execution errors such as SIGILL" —
    observed as a wall of ``cpu_aot_loader.cc`` errors in both round-3 driver
    artifacts, because ``.jax_cache/`` travels with the repo across builder/
    driver machines). Keying the cache directory by a hash of the host's CPU
    feature flags makes cross-host reuse structurally impossible while still
    sharing entries across processes on the same host."""
    import hashlib
    import platform as _platform

    feats = _platform.machine()
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                # x86 exposes "flags", arm64 "Features"
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    return "host-" + hashlib.sha1(feats.encode()).hexdigest()[:12]


def enable_compilation_cache(path: Optional[str] = None) -> Optional[str]:
    """Persistent XLA compilation cache (idempotent; on by default for the
    experiment harness).

    Scan/fused programs cost ~15-40 s each to compile on TPU; the cache
    brings a warm process start to seconds (measured round 3: 19 s → 2.9 s
    for one scan program). Default location is ``.jax_cache/<host-tag>/`` at
    the repo root (gitignored) — the per-host tag keeps AOT CPU code from one
    machine off another (SIGILL risk, see :func:`_host_cache_tag`). Override
    the base with ``$GDT_COMPILATION_CACHE`` (``"off"`` disables; the host
    tag is appended to any override too). Returns the active cache dir, or
    None when disabled/unsupported."""
    global _cache_enabled
    explicit = path or os.environ.get("GDT_COMPILATION_CACHE")
    path = explicit or os.path.join(_REPO_ROOT, ".jax_cache")
    if path == "off":
        return None
    # CPU backend: no persistence unless explicitly requested. jax's XLA:CPU
    # cache embeds AOT machine code whose recorded compile features include
    # tuning pseudo-features (+prefer-no-scatter/-gather) that the loader
    # then reports as cpu_aot_loader ERRORS on every load, EVEN ON THE HOST
    # THAT WROTE THEM (reproduced round 4; round 3's driver tails were full
    # of these) — and a real cross-host load risks SIGILL. Driver-facing CPU
    # runs therefore stay uncached (clean tails, no risk); the test suite
    # opts back in via $GDT_COMPILATION_CACHE (tests/conftest.py), where the
    # warm cache is worth minutes and the log noise lands in pytest output.
    if not explicit:
        platforms = getattr(jax.config, "jax_platforms", None) or os.environ.get(
            "JAX_PLATFORMS", ""
        )
        if (platforms or "").split(",")[0].strip().lower() == "cpu":
            return None
        # No explicit pin: the backend may still have FALLEN BACK to CPU
        # (dead chip, unpinned run) — ask the initialized backend itself.
        # This forces backend init, which every caller performs momentarily
        # anyway (the experiment constructors call this immediately before
        # building jitted programs).
        if jax.default_backend() == "cpu":
            return None
    path = os.path.join(path, _host_cache_tag())
    if _cache_enabled == path:  # already active at this exact directory
        return path
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _cache_enabled = path
        return path
    except Exception as exc:  # unsupported backend/jax version: run uncached
        logger.warning("compilation cache unavailable: %s", exc)
        return None


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> dict:
    """Multi-host coordination — the Spark-driver analog (SURVEY §2.4).

    The reference coordinates workers through a Spark driver
    (dl4jGANComputerVision.java:317-330); on TPU pods the host processes
    coordinate through the JAX distributed runtime and the devices talk over
    ICI/DCN via XLA collectives. On TPU pods with a metadata service all
    arguments auto-detect; pass them explicitly elsewhere. Safe to call when
    already initialized (no-op). Returns backend_info() for logging."""
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # already initialized (idempotent re-entry) is fine; propagate the
        # rest. jax phrases this either "already initialized" or
        # "distributed.initialize should only be called once" by version.
        msg = str(e).lower()
        if "already" not in msg and "only be called once" not in msg:
            raise
    info = backend_info()
    logger.info("Distributed runtime: %s", info)
    return info


@dataclasses.dataclass
class TpuEnvironment:
    """Runtime configuration (analog of the CUDA env block I3, SURVEY §2.1).

    Attributes:
      allow_multi_device: use all visible devices for the data mesh (analog of
        ``allowMultiGPU(true)``; on TPU this is the default and free).
      device_limit: cap the number of devices used (None = all). Replaces the
        reference's 2 GiB device-cache cap as the resource-limiting knob — HBM
        allocation itself is PJRT's job.
      mesh_axes: axis names for the device mesh; the reference only exercises
        data parallelism, but the mesh leaves a ``model`` axis open (SURVEY
        §2.3).
      verbose: log device/backend details (analog of ``setVerbose(true)``).
    """

    allow_multi_device: bool = True
    device_limit: Optional[int] = None
    mesh_axes: Tuple[str, ...] = ("data",)
    verbose: bool = False

    def devices(self) -> list:
        devs = jax.devices()
        if not self.allow_multi_device:
            devs = devs[:1]
        if self.device_limit is not None:
            devs = devs[: self.device_limit]
        return devs

    def device_count(self) -> int:
        return len(self.devices())

    def make_mesh(self, axis_sizes: Optional[Sequence[int]] = None) -> jax.sharding.Mesh:
        """Build the device mesh. With the default single ``data`` axis, all
        visible devices form a 1-D data-parallel mesh — the TPU-native
        equivalent of Spark's ``local[4]`` worker pool
        (dl4jGANComputerVision.java:318), except the "workers" are chips on ICI.
        """
        devs = self.devices()
        if axis_sizes is None:
            axis_sizes = [len(devs)] + [1] * (len(self.mesh_axes) - 1)
        if int(np.prod(axis_sizes)) != len(devs):
            raise ValueError(
                f"mesh axis sizes {tuple(axis_sizes)} do not cover {len(devs)} devices"
            )
        mesh_devices = np.asarray(devs).reshape(axis_sizes)
        mesh = jax.sharding.Mesh(mesh_devices, self.mesh_axes)
        if self.verbose:
            logger.info("Mesh: %s over %s", dict(zip(self.mesh_axes, mesh_devices.shape)), backend_info())
        return mesh

    def log_backend(self) -> None:
        info = backend_info()
        logger.info("Execution backend: %s", info)
