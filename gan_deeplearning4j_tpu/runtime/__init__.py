"""Runtime core: dtype policy, seeded PRNG streams, array factory, device environment.

TPU-native replacement for the reference's ND4J/CUDA runtime layer
(Nd4j.setDataType / CudaEnvironment / Nd4j factory surface,
dl4jGANComputerVision.java:103-115).
"""

from gan_deeplearning4j_tpu.runtime.dtype import (
    get_default_dtype,
    set_default_dtype,
    default_dtype_scope,
)
from gan_deeplearning4j_tpu.runtime.prng import RngStream
from gan_deeplearning4j_tpu.runtime.environment import (
    TpuEnvironment,
    backend_info,
    enable_compilation_cache,
    initialize_distributed,
)

__all__ = [
    "get_default_dtype",
    "set_default_dtype",
    "default_dtype_scope",
    "RngStream",
    "TpuEnvironment",
    "backend_info",
    "enable_compilation_cache",
    "initialize_distributed",
]
