"""Global dtype policy.

TPU-native analog of ``Nd4j.setDataType(DataBuffer.Type.FLOAT)``
(reference: dl4jGANComputerVision.java:105). The reference pins a single global
float32 dtype; on TPU we additionally expose a *compute* dtype so matmuls/convs
can run in bfloat16 on the MXU while parameters stay float32.
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

_state = threading.local()


def _get_state():
    if not hasattr(_state, "default_dtype"):
        _state.default_dtype = jnp.float32
        _state.compute_dtype = None  # None => same as default
    return _state


def set_default_dtype(dtype) -> None:
    """Set the global parameter/storage dtype (reference default: float32)."""
    _get_state().default_dtype = jnp.dtype(dtype)


def get_default_dtype():
    return _get_state().default_dtype


def set_compute_dtype(dtype) -> None:
    """Set the MXU compute dtype (e.g. ``jnp.bfloat16``). ``None`` disables mixed
    precision and computes in the default dtype."""
    _get_state().compute_dtype = None if dtype is None else jnp.dtype(dtype)


def get_compute_dtype():
    st = _get_state()
    return st.compute_dtype if st.compute_dtype is not None else st.default_dtype


@contextlib.contextmanager
def default_dtype_scope(dtype):
    st = _get_state()
    prev = st.default_dtype
    st.default_dtype = jnp.dtype(dtype)
    try:
        yield
    finally:
        st.default_dtype = prev


def parse_compute_dtype(name):
    """Map a config/CLI string to a compute dtype: ``"bf16"``/``"bfloat16"``
    → bfloat16 mixed precision; ``None``/``"f32"``/``"float32"`` → full
    precision (None, i.e. compute in the default dtype)."""
    if not isinstance(name, str):
        return name
    key = name.lower()
    if key in ("bf16", "bfloat16"):
        return jnp.bfloat16
    if key in ("f32", "float32", "none", ""):
        return None
    raise ValueError(f"unknown compute dtype {name!r} (use 'bf16' or 'f32')")


@contextlib.contextmanager
def compute_dtype_scope(dtype):
    st = _get_state()
    prev = st.compute_dtype
    st.compute_dtype = None if dtype is None else jnp.dtype(dtype)
    try:
        yield
    finally:
        st.compute_dtype = prev
