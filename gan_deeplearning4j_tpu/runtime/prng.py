"""Seeded PRNG streams.

The reference seeds every graph with one integer seed (seed 666 at
dl4jGANComputerVision.java:121,176,231) and draws from a global stateful RNG
(Nd4j.randn/rand). JAX PRNG is functional; ``RngStream`` wraps key-splitting in
a small stateful facade so framework code (array factory, init, dropout) gets
DL4J-like ergonomics while staying reproducible and jit-friendly (keys are
split *outside* traced code).
"""

from __future__ import annotations

import jax


class RngStream:
    """A stateful stream of PRNG keys derived from one seed.

    Each call to :meth:`next_key` returns a fresh key; the stream is
    deterministic given the seed. Not safe for use inside ``jax.jit`` traces —
    draw keys outside and pass them in.
    """

    def __init__(self, seed: int):
        self._seed = int(seed)
        self._initial_key = jax.random.PRNGKey(self._seed)
        self._key = self._initial_key

    @property
    def seed(self) -> int:
        return self._seed

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def next_keys(self, n: int):
        self._key, *subs = jax.random.split(self._key, n + 1)
        return list(subs)

    def fork(self) -> "RngStream":
        """A new independent stream rooted at this one's next key; the child's
        ``reset`` rewinds to its own root, not the parent's."""
        child = RngStream(self._seed)
        child._initial_key = self.next_key()
        child._key = child._initial_key
        return child

    def reset(self) -> None:
        self._key = self._initial_key
