"""gan_deeplearning4j_tpu — a TPU-native deep-learning framework.

A from-scratch JAX/XLA framework with the capability surface of the reference
``hamaadshah/gan_deeplearning4j`` stack (DL4J ComputationGraph + ND4J + Spark
parameter averaging + cuDNN kernels), re-designed TPU-first:

- named-layer computation graphs with per-layer updaters, LR-0 freezing,
  transfer-learning graph surgery, named-parameter get/set
  (reference binding: dl4jGANComputerVision.java:118-314,337-364,429-542);
- ops lowered through XLA to the TPU MXU (conv/dense as lax convolutions and
  dot_generals in NHWC, bf16-friendly) instead of cuDNN/cuBLAS kernels
  (reference: Java/pom.xml:119-128);
- data parallelism via jax.sharding Mesh + XLA all-reduce over ICI instead of
  Spark synchronous parameter averaging (reference:
  dl4jGANComputerVision.java:317-330);
- device-resident data pipeline, checkpointing with updater state, and an
  alternating GAN training harness (reference: dl4jGANComputerVision.java:408-621).

The top-level namespace is LAZY (PEP 562): importing the package must not
import jax. Two consumers depend on that — bench.py's parent process (which
must stay killable while a dead chip can hang ``import jax`` inside native
code for minutes) and the jaxlint analyzer
(``python -m gan_deeplearning4j_tpu.analysis``), which has to run in any
container regardless of the installed accelerator stack. Submodule imports
(``from gan_deeplearning4j_tpu.harness import ...``) behave exactly as
before; only the convenience re-exports below defer.
"""

__version__ = "0.1.0"

# name -> (module to import, attribute to take from it; None = the module)
_LAZY_EXPORTS = {
    "TpuEnvironment": ("gan_deeplearning4j_tpu.runtime.environment",
                       "TpuEnvironment"),
    "backend_info": ("gan_deeplearning4j_tpu.runtime.environment",
                     "backend_info"),
    "factory": ("gan_deeplearning4j_tpu.runtime.factory", None),
    "get_default_dtype": ("gan_deeplearning4j_tpu.runtime.dtype",
                          "get_default_dtype"),
    "set_default_dtype": ("gan_deeplearning4j_tpu.runtime.dtype",
                          "set_default_dtype"),
}

__all__ = [*_LAZY_EXPORTS, "__version__"]


def __getattr__(name):
    try:
        module_name, attr = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    module = importlib.import_module(module_name)
    value = module if attr is None else getattr(module, attr)
    globals()[name] = value  # cache: __getattr__ runs once per name
    return value


def __dir__():
    return sorted({*globals(), *_LAZY_EXPORTS})
