"""gan_deeplearning4j_tpu — a TPU-native deep-learning framework.

A from-scratch JAX/XLA framework with the capability surface of the reference
``hamaadshah/gan_deeplearning4j`` stack (DL4J ComputationGraph + ND4J + Spark
parameter averaging + cuDNN kernels), re-designed TPU-first:

- named-layer computation graphs with per-layer updaters, LR-0 freezing,
  transfer-learning graph surgery, named-parameter get/set
  (reference binding: dl4jGANComputerVision.java:118-314,337-364,429-542);
- ops lowered through XLA to the TPU MXU (conv/dense as lax convolutions and
  dot_generals in NHWC, bf16-friendly) instead of cuDNN/cuBLAS kernels
  (reference: Java/pom.xml:119-128);
- data parallelism via jax.sharding Mesh + XLA all-reduce over ICI instead of
  Spark synchronous parameter averaging (reference:
  dl4jGANComputerVision.java:317-330);
- device-resident data pipeline, checkpointing with updater state, and an
  alternating GAN training harness (reference: dl4jGANComputerVision.java:408-621).
"""

__version__ = "0.1.0"

from gan_deeplearning4j_tpu.runtime.environment import TpuEnvironment, backend_info
from gan_deeplearning4j_tpu.runtime import factory
from gan_deeplearning4j_tpu.runtime.dtype import get_default_dtype, set_default_dtype

__all__ = [
    "TpuEnvironment",
    "backend_info",
    "factory",
    "get_default_dtype",
    "set_default_dtype",
    "__version__",
]
