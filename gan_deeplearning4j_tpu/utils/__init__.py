"""Utilities: model serialization, profiling scopes, metrics logging."""

from gan_deeplearning4j_tpu.utils.serializer import (
    ModelSerializer,
    read_model,
    write_model,
)

__all__ = ["ModelSerializer", "read_model", "write_model"]
