"""Phase timing + device tracing (SURVEY §5 tracing/profiling).

The reference's only observability is CUDA-backend verbosity and phase-named
log lines ("Training discriminator!" etc., dl4jGANComputerVision.java:424,
469,515). Here each phase of the training loop runs inside a timing scope,
and ``device_trace`` wraps ``jax.profiler.trace`` for TensorBoard/Perfetto
captures of the XLA timeline when deeper inspection is needed.

Since the telemetry plane landed (docs/OBSERVABILITY.md), both timers here
are REGISTRY-BACKED: the per-phase/per-stage sample streams live in
histograms of the process-wide :mod:`gan_deeplearning4j_tpu.telemetry`
registry (``train_phase_seconds{phase=...}``,
``serve_stage_seconds{stage=...}``), so ``/metrics``, Prometheus scrapes,
BENCH artifacts, and these objects' own ``report()``/``summary_ms()`` all
read the same samples. The Python API is unchanged — ``totals``/``counts``
(PhaseTimer) and ``busy``/``occupancy()`` (StageStats) stay per-instance,
which is what their callers aggregate over one run.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional, Sequence

import jax

from gan_deeplearning4j_tpu.telemetry.registry import (
    get_registry,
    percentiles,
)
from gan_deeplearning4j_tpu.telemetry.trace import TRACER

logger = logging.getLogger(__name__)

__all__ = ["percentiles", "PhaseTimer", "StageStats", "device_trace"]


class PhaseTimer:
    """Accumulates wall-clock per named phase across loop iterations.

    Keeps the most recent ``max_samples`` per-call durations per phase so
    ``report()``/``percentile()`` can state tail latency (p50/p95/p99), not
    just the mean — a mean hides exactly the stalls (recompiles, host syncs)
    worth finding. The samples live in the telemetry registry histogram
    ``train_phase_seconds`` (one stream for this object, ``/metrics``, and
    BENCH artifacts); ``totals``/``counts`` stay per-instance."""

    def __init__(self, max_samples: int = 65536,
                 metric: str = "train_phase_seconds", registry=None):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self._hist = (registry or get_registry()).histogram(
            metric, "wall seconds per named training phase",
            labelnames=("phase",), max_samples=max_samples,
        )
        # per-phase series resolved once and cached (the labels() parse is
        # not for the per-iteration path), mirroring the batcher's idiom
        self._children: Dict[str, object] = {}
        self.samples: Dict = _SampleView(self._children)

    def _child(self, name: str):
        child = self._children.get(name)
        if child is None:
            child = self._hist.labels(phase=name)
            self._children[name] = child
        return child

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[list]:
        """Time one phase. The scope yields a sink list: append the phase's
        output arrays to it and the timer blocks on them before stopping the
        clock, so device execution is billed to this phase rather than to
        whichever later phase happens to synchronize (XLA dispatch is async —
        without a sync the scope measures Python only)."""
        sink: list = []
        start = time.perf_counter()
        try:
            yield sink
        finally:
            if sink:
                jax.block_until_ready(sink)
            end = time.perf_counter()
            elapsed = end - start
            self.totals[name] += elapsed
            self.counts[name] += 1
            self._child(name).observe(elapsed)
            if TRACER.enabled:  # the harness's phases double as trace
                # spans, so a training trace and a serving trace fold
                # with the same tooling (guarded: no per-phase f-string
                # when tracing is off)
                TRACER.complete(f"train.{name}", start, end)

    def mean(self, name: str) -> float:
        c = self.counts.get(name, 0)
        return self.totals[name] / c if c else 0.0

    def _percentiles(self, name: str, qs=(50, 95, 99)) -> Dict[str, float]:
        # through Histogram.percentiles (copies under the series lock):
        # another thread may be observing into the same process-wide
        # series while this reads
        child = self._children.get(name)
        return child.percentiles(qs) if child is not None else {}

    def percentile(self, name: str, q: float) -> float:
        return self._percentiles(name, (q,)).get(f"p{q:g}", 0.0)

    def report(self) -> str:
        rows = sorted(self.totals.items(), key=lambda kv: -kv[1])
        out = []
        for name, total in rows:
            ps = self._percentiles(name)
            tail = "  ".join(f"{k} {v*1e3:8.2f}ms" for k, v in ps.items())
            out.append(
                f"{name:>24s}: total {total:8.3f}s  mean {self.mean(name)*1e3:8.2f}ms  "
                f"{tail}  n={self.counts[name]}"
            )
        return "\n".join(out)


class _SampleView:
    """Dict-like read view over the timer's per-phase sample deques, so
    ``timer.samples[name]`` keeps working while the storage lives in the
    registry (the single-sample-stream contract). Read-only: probing a
    name that was never timed returns empty instead of materializing a
    phantom count-0 series in /metrics."""

    def __init__(self, children: Dict[str, object]):
        self._children = children

    def __getitem__(self, name: str):
        child = self._children.get(name)
        return child.samples if child is not None else ()

    def get(self, name: str, default=()):
        child = self._children.get(name)
        return child.samples if child is not None else default

    def keys(self):
        return list(self._children)


class StageStats:
    """Busy-time + latency accounting for a fixed set of pipeline stages.

    The serving batcher splits a flush into assemble (host staging +
    async dispatch), device (wait-until-ready), and complete (scatter to
    callers); each stage records its per-flush duration here.
    ``occupancy()`` is busy-seconds / wall-seconds since construction —
    the direct read on whether the pipeline overlaps (assemble occupancy
    ≪ 1 while device occupancy ≈ 1 means the host keeps the device fed).
    Per-stage samples live in the registry histogram
    ``serve_stage_seconds`` (its ``sum`` is the process-wide busy time);
    ``busy`` and the wall-clock origin stay per-instance, and callers
    serialize ``add`` per stage (the batcher records each stage from the
    one thread that runs it)."""

    def __init__(self, stages: Sequence[str], max_samples: int = 65536,
                 metric: str = "serve_stage_seconds", registry=None):
        self._t0 = time.monotonic()
        self.busy: Dict[str, float] = {s: 0.0 for s in stages}
        hist = (registry or get_registry()).histogram(
            metric, "busy seconds per pipeline stage, per flush",
            labelnames=("stage",), max_samples=max_samples,
        )
        self._children = {s: hist.labels(stage=s) for s in stages}
        self.samples: Dict = {s: c.samples for s, c in self._children.items()}

    def add(self, stage: str, seconds: float) -> None:
        self.busy[stage] += seconds
        self._children[stage].observe(seconds)

    def occupancy(self) -> Dict[str, float]:
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        return {s: b / elapsed for s, b in self.busy.items()}

    def summary_ms(self) -> Dict[str, Dict[str, float]]:
        # read through Histogram.percentiles (copies under the series lock):
        # the worker/completer threads observe concurrently with a /metrics
        # read, and iterating a deque mid-append raises
        return {
            s: {k: v * 1e3 for k, v in child.percentiles().items()}
            for s, child in self._children.items()
            if child.count
        }


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture an XLA device trace under ``log_dir`` (viewable in
    TensorBoard's profile tab / Perfetto). No-op when ``log_dir`` is None.
    For captures triggered on a RUNNING process, see
    ``telemetry.device.capture_device_trace`` and its serving/supervisor
    hooks."""
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
