"""Phase timing + device tracing (SURVEY §5 tracing/profiling).

The reference's only observability is CUDA-backend verbosity and phase-named
log lines ("Training discriminator!" etc., dl4jGANComputerVision.java:424,
469,515). Here each phase of the training loop runs inside a timing scope,
and ``device_trace`` wraps ``jax.profiler.trace`` for TensorBoard/Perfetto
captures of the XLA timeline when deeper inspection is needed.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict, deque
from typing import Dict, Iterable, Iterator, Optional, Sequence

import jax

logger = logging.getLogger(__name__)


def percentiles(values: Iterable[float], qs: Sequence[float] = (50, 95, 99)) -> Dict[str, float]:
    """Nearest-rank percentiles of ``values`` as ``{"p50": ..., ...}``
    (empty dict for no samples). Shared by PhaseTimer.report and the serving
    latency metrics — one definition so BENCH artifacts and /metrics agree."""
    import math

    data = sorted(float(v) for v in values)
    if not data:
        return {}
    out = {}
    for q in qs:
        rank = max(1, min(len(data), math.ceil(q / 100.0 * len(data))))
        out[f"p{q:g}"] = data[rank - 1]
    return out


class PhaseTimer:
    """Accumulates wall-clock per named phase across loop iterations.

    Keeps the most recent ``max_samples`` per-call durations per phase so
    ``report()``/``percentile()`` can state tail latency (p50/p95/p99), not
    just the mean — a mean hides exactly the stalls (recompiles, host syncs)
    worth finding."""

    def __init__(self, max_samples: int = 65536):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.samples: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=max_samples)
        )

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[list]:
        """Time one phase. The scope yields a sink list: append the phase's
        output arrays to it and the timer blocks on them before stopping the
        clock, so device execution is billed to this phase rather than to
        whichever later phase happens to synchronize (XLA dispatch is async —
        without a sync the scope measures Python only)."""
        sink: list = []
        start = time.perf_counter()
        try:
            yield sink
        finally:
            if sink:
                jax.block_until_ready(sink)
            elapsed = time.perf_counter() - start
            self.totals[name] += elapsed
            self.counts[name] += 1
            self.samples[name].append(elapsed)

    def mean(self, name: str) -> float:
        c = self.counts.get(name, 0)
        return self.totals[name] / c if c else 0.0

    def percentile(self, name: str, q: float) -> float:
        return percentiles(self.samples.get(name, ()), (q,)).get(f"p{q:g}", 0.0)

    def report(self) -> str:
        rows = sorted(self.totals.items(), key=lambda kv: -kv[1])
        out = []
        for name, total in rows:
            ps = percentiles(self.samples.get(name, ()))
            tail = "  ".join(f"{k} {v*1e3:8.2f}ms" for k, v in ps.items())
            out.append(
                f"{name:>24s}: total {total:8.3f}s  mean {self.mean(name)*1e3:8.2f}ms  "
                f"{tail}  n={self.counts[name]}"
            )
        return "\n".join(out)


class StageStats:
    """Busy-time + latency accounting for a fixed set of pipeline stages.

    The serving batcher splits a flush into assemble (host staging +
    async dispatch), device (wait-until-ready), and complete (scatter to
    callers); each stage records its per-flush duration here.
    ``occupancy()`` is busy-seconds / wall-seconds since construction —
    the direct read on whether the pipeline overlaps (assemble occupancy
    ≪ 1 while device occupancy ≈ 1 means the host keeps the device fed).
    Not synchronized: callers serialize ``add`` per stage (the batcher
    records each stage from the one thread that runs it)."""

    def __init__(self, stages: Sequence[str], max_samples: int = 65536):
        self._t0 = time.monotonic()
        self.busy: Dict[str, float] = {s: 0.0 for s in stages}
        self.samples: Dict[str, deque] = {
            s: deque(maxlen=max_samples) for s in stages
        }

    def add(self, stage: str, seconds: float) -> None:
        self.busy[stage] += seconds
        self.samples[stage].append(seconds)

    def occupancy(self) -> Dict[str, float]:
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        return {s: b / elapsed for s, b in self.busy.items()}

    def summary_ms(self) -> Dict[str, Dict[str, float]]:
        return {
            s: {k: v * 1e3 for k, v in percentiles(samples).items()}
            for s, samples in self.samples.items()
            if samples
        }


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture an XLA device trace under ``log_dir`` (viewable in
    TensorBoard's profile tab / Perfetto). No-op when ``log_dir`` is None."""
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
