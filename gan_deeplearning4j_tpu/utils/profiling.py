"""Phase timing + device tracing (SURVEY §5 tracing/profiling).

The reference's only observability is CUDA-backend verbosity and phase-named
log lines ("Training discriminator!" etc., dl4jGANComputerVision.java:424,
469,515). Here each phase of the training loop runs inside a timing scope,
and ``device_trace`` wraps ``jax.profiler.trace`` for TensorBoard/Perfetto
captures of the XLA timeline when deeper inspection is needed.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

import jax

logger = logging.getLogger(__name__)


class PhaseTimer:
    """Accumulates wall-clock per named phase across loop iterations."""

    def __init__(self):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[list]:
        """Time one phase. The scope yields a sink list: append the phase's
        output arrays to it and the timer blocks on them before stopping the
        clock, so device execution is billed to this phase rather than to
        whichever later phase happens to synchronize (XLA dispatch is async —
        without a sync the scope measures Python only)."""
        sink: list = []
        start = time.perf_counter()
        try:
            yield sink
        finally:
            if sink:
                jax.block_until_ready(sink)
            elapsed = time.perf_counter() - start
            self.totals[name] += elapsed
            self.counts[name] += 1

    def mean(self, name: str) -> float:
        c = self.counts.get(name, 0)
        return self.totals[name] / c if c else 0.0

    def report(self) -> str:
        rows = sorted(self.totals.items(), key=lambda kv: -kv[1])
        return "\n".join(
            f"{name:>24s}: total {total:8.3f}s  mean {self.mean(name)*1e3:8.2f}ms  n={self.counts[name]}"
            for name, total in rows
        )


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture an XLA device trace under ``log_dir`` (viewable in
    TensorBoard's profile tab / Perfetto). No-op when ``log_dir`` is None."""
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
