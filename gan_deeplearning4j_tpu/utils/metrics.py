"""Per-step scalar metrics (SURVEY §5 metrics/logging).

The reference logs phase names but never a single loss value; quality metrics
live offline in the notebook. Here every loop iteration emits structured
scalars (D-loss, G-loss, CV-loss, images/sec) through the standard logger and
optionally to a JSONL file for offline analysis — the quantitative logging
the reference lacks, required by the bench harness anyway (SURVEY §6).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, Optional

logger = logging.getLogger("gan_deeplearning4j_tpu.metrics")


class MetricsLogger:
    """Step-keyed scalar sink: stdlib logging + optional JSONL file."""

    def __init__(self, jsonl_path: Optional[str] = None):
        self.jsonl_path = jsonl_path
        self._fh = None
        if jsonl_path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(jsonl_path)), exist_ok=True)
            self._fh = open(jsonl_path, "a", buffering=1)
        self.history: list = []

    def log(self, step: int, scalars: Dict[str, float]) -> None:
        record = {"step": int(step), "time": time.time()}
        record.update({k: float(v) for k, v in scalars.items()})
        self.history.append(record)
        logger.info(
            "step %d | %s",
            step,
            " ".join(f"{k}={v:.5g}" for k, v in record.items() if k not in ("step", "time")),
        )
        if self._fh is not None:
            self._fh.write(json.dumps(record) + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
