"""Model checkpointing — the ``ModelSerializer`` analog (SURVEY §2.2 D12, §5).

The reference saves all four models every loop iteration as zips with updater
state included: ``ModelSerializer.writeModel(model, file, saveUpdater=true)``
(dl4jGANComputerVision.java:605-619). Restore is never exercised there but the
format implies it; here both directions exist.

Checkpoint = one zip holding:
- ``topology.json`` — the graph config/topology (``ComputationGraph.to_dict``),
  enough to rebuild the graph without the defining code path;
- ``arrays.npz`` — every named parameter and (optionally) per-layer updater
  state, flattened to ``params/<layer>/<name>`` / ``updater/<layer>/<param>/
  <slot>`` keys;
- ``meta.json`` — step counter + format version.

Arrays cross to host exactly once per save (one batched ``jax.device_get``),
not per-parameter — the scalar-read-per-value pathology the reference's CSV
export has (SURVEY §3.3 hot loop 3) is avoided at every host boundary here.
"""

from __future__ import annotations

import hashlib
import heapq
import io
import json
import os
import tempfile
import zipfile
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

FORMAT_VERSION = 1


def member_digest(data: bytes) -> str:
    """Content digest of one checkpoint member (``sha256:<hex>``) — the
    currency of both the in-zip manifest (``meta.json``'s
    ``member_digests``) and the resilience store's generation manifests."""
    return "sha256:" + hashlib.sha256(data).hexdigest()


def _flatten(prefix: str, tree: Dict, out: Dict[str, np.ndarray]) -> None:
    for key, value in tree.items():
        path = f"{prefix}/{key}"
        if isinstance(value, dict):
            _flatten(path, value, out)
        else:
            out[path] = value


def _unflatten(flat: Dict[str, np.ndarray], prefix: str) -> Dict:
    tree: Dict = {}
    plen = len(prefix) + 1
    for path, value in flat.items():
        if not path.startswith(prefix + "/"):
            continue
        node = tree
        parts = path[plen:].split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = jnp.asarray(value)
    return tree


def _npz_encode(arrays: Dict[str, np.ndarray]) -> Tuple[bytes, Dict[str, str]]:
    """Serialize a flat dict of host arrays to npz bytes. npz cannot
    represent ml_dtypes extension types (bfloat16 round-trips as raw
    void16, losing the dtype) — such arrays travel as uint16 bit patterns
    with the real dtype recorded in the returned map."""
    ext_dtypes: Dict[str, str] = {}
    for key, value in list(arrays.items()):
        if value.dtype == jnp.bfloat16:
            arrays[key] = np.asarray(value).view(np.uint16)
            ext_dtypes[key] = "bfloat16"
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue(), ext_dtypes


def _npz_decode(npz_bytes: bytes, ext_dtypes: Dict[str, str]) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(npz_bytes)) as npz:
        flat = {k: npz[k] for k in npz.files}
    for key, name in ext_dtypes.items():
        flat[key] = flat[key].view(jnp.dtype(name))
    return flat


def _element_count(value) -> int:
    """Leaf size (elements) for the balanced partition: arrays/structs by
    shape, ints verbatim, anything else (step scalars, None placeholders)
    counts 1."""
    if value is None:
        return 1
    if isinstance(value, (int, np.integer)):
        return max(1, int(value))
    shape = getattr(value, "shape", None)
    if shape is None:
        return 1
    n = 1
    for d in shape:
        n *= int(d)
    return max(1, n)


def shard_assignment(sizes: Dict[str, int], shard_count: int) -> Dict[str, int]:
    """The deterministic SIZE-BALANCED partition of the flat key space:
    key -> owning shard. Within each kind bucket (the second path
    component — ``params`` / ``updater`` / ``step``), keys go largest-
    first to the least-loaded shard (ties: lowest index). Derived from
    sorting and sizes alone, so N processes — checkpoint writers AND the
    update-sharding compute plan — agree without communicating, the same
    property the original round-robin had. Per-bucket balancing is what
    gives the compute half its memory win: resident updater bytes per
    shard stay ≈ total/N, where strict round-robin over sorted keys
    systematically parks every big conv ``W`` cache on one shard (the
    W/b key alternation keeps equal parities together)."""
    if shard_count < 1:
        raise ValueError("shard_count must be >= 1")

    def bucket(key: str) -> str:
        parts = key.split("/")
        return parts[1] if len(parts) > 1 else ""

    assign: Dict[str, int] = {}
    for b in sorted({bucket(k) for k in sizes}):
        order = sorted((k for k in sizes if bucket(k) == b),
                       key=lambda k: (-_element_count(sizes[k]), k))
        heap = [(0, i) for i in range(shard_count)]
        heapq.heapify(heap)
        for key in order:
            load, i = heapq.heappop(heap)
            assign[key] = i
            heapq.heappush(heap, (load + _element_count(sizes[key]), i))
    return assign


def shard_keys(keys, shard_index: int, shard_count: int):
    """The deterministic key partition of the mesh checkpoint plane.

    Given a bare key list, shard ``shard_index`` of ``shard_count`` owns
    every ``shard_count``-th key of the SORTED key list (PR 9's original
    round-robin — count-balanced, stable across processes). Given a
    MAPPING (flat key -> array/struct/size), ownership is the
    size-balanced :func:`shard_assignment` instead, which the
    update-sharding compute plan shares — compute shard k then holds
    exactly the updater keys checkpoint shard k writes, at ≈ 1/N of the
    bytes. Either way the union over all shards is exactly the full key
    set — the property elastic restore merges on (restore never depends
    on WHICH shard held a key, so generations written under either rule
    keep restoring)."""
    if shard_count < 1:
        raise ValueError("shard_count must be >= 1")
    if not 0 <= shard_index < shard_count:
        raise ValueError(f"shard_index {shard_index} outside "
                         f"[0, {shard_count})")
    if isinstance(keys, dict):
        assign = shard_assignment(keys, shard_count)
        return sorted(k for k, s in assign.items() if s == shard_index)
    return sorted(keys)[shard_index::shard_count]


def write_state_shard(path: str, flat_arrays: Dict[str, np.ndarray],
                      meta: Optional[dict] = None) -> None:
    """One shard of a mesh checkpoint: a zip of ``arrays.npz`` (this
    shard's flat ``<model>/params|updater/...`` keys only) + ``meta.json``
    with per-member digests — the same self-verifying armor as
    :func:`write_model`, minus topology (a mesh restore rebuilds onto the
    live experiment's graphs). Lands temp+fsync+rename so the mesh
    staging dir never holds a torn shard under a committed vote."""
    arrays = dict(flat_arrays)
    arrays = jax.device_get(arrays)  # one batched device->host transfer
    npz_bytes, ext_dtypes = _npz_encode(arrays)
    payload = {
        "format_version": FORMAT_VERSION,
        "array_dtypes": ext_dtypes,
        "keys": sorted(arrays),
        **(meta or {}),
        "member_digests": {"arrays.npz": member_digest(npz_bytes)},
    }
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            with zipfile.ZipFile(fh, "w", zipfile.ZIP_DEFLATED) as zf:
                zf.writestr("meta.json", json.dumps(payload))
                zf.writestr("arrays.npz", npz_bytes)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def read_state_shard(path: str) -> Tuple[Dict[str, np.ndarray], dict]:
    """Load one mesh-checkpoint shard: (flat arrays, meta). Corruption or
    truncation raises ``ValueError`` — same contract as
    :func:`read_model`, so the store's quarantine machinery and the
    elastic restore path judge shards and full checkpoints identically."""
    try:
        with zipfile.ZipFile(path, "r") as zf:
            meta = json.loads(zf.read("meta.json"))
            if meta["format_version"] > FORMAT_VERSION:
                raise ValueError(
                    f"shard format {meta['format_version']} is newer than "
                    f"supported {FORMAT_VERSION}"
                )
            npz_bytes = zf.read("arrays.npz")
            want = meta.get("member_digests", {}).get("arrays.npz")
            if want is not None and member_digest(npz_bytes) != want:
                raise ValueError(
                    f"shard {path!r} member 'arrays.npz' fails digest "
                    f"verification (expected {want}) — corrupted bytes"
                )
    except zipfile.BadZipFile as exc:
        raise ValueError(
            f"corrupted or truncated shard {path!r}: {exc}"
        ) from exc
    except KeyError as exc:
        raise ValueError(
            f"shard {path!r} is missing a required member: {exc}"
        ) from exc
    flat = _npz_decode(npz_bytes, meta.get("array_dtypes", {}))
    return flat, meta


def write_model(path: str, graph, state, save_updater: bool = True) -> None:
    """Serialize graph topology + params (+ updater state) to ``path``.

    ``state`` is a TrainState, or a bare params dict (then there is no
    updater state regardless of ``save_updater``).
    """
    params = getattr(state, "params", state)
    opt_state = getattr(state, "opt_state", None) if save_updater else None
    step = getattr(state, "step", None)

    arrays: Dict[str, np.ndarray] = {}
    _flatten("params", params, arrays)
    if opt_state is not None:
        _flatten("updater", opt_state, arrays)
    arrays = jax.device_get(arrays)  # one batched device->host transfer
    # bf16 param storage travels as tagged uint16 bit patterns (round-4
    # VERDICT item 3) — shared with the mesh shard format
    npz_bytes, ext_dtypes = _npz_encode(arrays)
    topology_bytes = json.dumps(graph.to_dict()).encode()
    meta = {
        "format_version": FORMAT_VERSION,
        "step": int(step) if step is not None else 0,
        "has_updater": opt_state is not None,
        "array_dtypes": ext_dtypes,
        # per-member content digests: read_model re-hashes every member
        # against these, so a flipped bit ANYWHERE in the payload — not just
        # a truncation the zip CRC happens to catch — fails loudly. The
        # resilience store's corruption quarantine is built on this check.
        "member_digests": {
            "topology.json": member_digest(topology_bytes),
            "arrays.npz": member_digest(npz_bytes),
        },
    }

    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    # write-then-rename so a crash mid-save never corrupts the previous
    # checkpoint (the per-iteration overwrite pattern of the reference)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            with zipfile.ZipFile(fh, "w", zipfile.ZIP_DEFLATED) as zf:
                zf.writestr("topology.json", topology_bytes)
                zf.writestr("meta.json", json.dumps(meta))
                zf.writestr("arrays.npz", npz_bytes)
            # flush to stable storage BEFORE the rename publishes the file:
            # without the fsync a crash can publish a name pointing at
            # not-yet-written bytes — exactly the truncated zip the serving
            # loader must never see
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def read_model(path: str, load_updater: bool = True) -> Tuple[object, Dict, Optional[Dict], int]:
    """Load a checkpoint: returns (graph, params, opt_state_or_None, step).

    The graph is rebuilt from the stored topology, so a checkpoint is
    self-contained (restorable without the code that defined the model).
    A corrupted or truncated file raises ``ValueError`` — a serving loader
    must reject a half-written artifact loudly, never half-load it."""
    from gan_deeplearning4j_tpu.nn.graph import ComputationGraph

    try:
        with zipfile.ZipFile(path, "r") as zf:
            topology_bytes = zf.read("topology.json")
            meta = json.loads(zf.read("meta.json"))
            if meta["format_version"] > FORMAT_VERSION:
                raise ValueError(
                    f"checkpoint format {meta['format_version']} is newer than "
                    f"supported {FORMAT_VERSION}"
                )
            npz_bytes = zf.read("arrays.npz")
            # digest verification (same contract as the truncation checks:
            # corruption raises ValueError, never a silent partial load).
            # Checkpoints written before member_digests existed carry no
            # digests and skip the check.
            for name, data in (("topology.json", topology_bytes),
                               ("arrays.npz", npz_bytes)):
                want = meta.get("member_digests", {}).get(name)
                if want is not None and member_digest(data) != want:
                    raise ValueError(
                        f"checkpoint {path!r} member {name!r} fails digest "
                        f"verification (expected {want}) — corrupted bytes"
                    )
            topology = json.loads(topology_bytes)
    except zipfile.BadZipFile as exc:
        raise ValueError(
            f"corrupted or truncated checkpoint {path!r}: {exc}"
        ) from exc
    except KeyError as exc:
        raise ValueError(
            f"checkpoint {path!r} is missing a required member: {exc}"
        ) from exc
    try:
        flat = _npz_decode(npz_bytes, meta.get("array_dtypes", {}))
    except zipfile.BadZipFile as exc:
        # a pre-member_digests checkpoint can carry a torn npz the outer
        # zip CRC missed; digest-carrying checkpoints never reach here
        raise ValueError(
            f"corrupted or truncated checkpoint {path!r}: {exc}"
        ) from exc

    graph = ComputationGraph.from_dict(topology)
    params = _unflatten(flat, "params")
    opt_state = None
    if load_updater and meta["has_updater"]:
        opt_state = _unflatten(flat, "updater")
    return graph, params, opt_state, meta["step"]


class ModelSerializer:
    """DL4J-shaped static facade (``ModelSerializer.writeModel/restore``)."""

    write_model = staticmethod(write_model)
    read_model = staticmethod(read_model)

    @staticmethod
    def restore_train_state(path: str, trainer):
        """Rebuild a trainer-ready TrainState from a checkpoint (resume — the
        capability the reference's format implies but never calls)."""
        from gan_deeplearning4j_tpu.parallel.trainer import TrainState

        _, params, opt_state, step = read_model(path)
        if opt_state is None:
            # always the TREE-form init: checkpoints serialize the tree
            # contract regardless of the trainer's compute layout (an
            # update-sharding trainer exposes its replicated base)
            opt = getattr(trainer.optimizer, "base", trainer.optimizer)
            opt_state = opt.init(params)
        return TrainState(params, opt_state, jnp.asarray(step, jnp.int32))
