"""WGAN-GP — BASELINE.md config 5: Wasserstein GAN with gradient penalty on
CIFAR-10-shaped data, the grad-of-grad config ("lowered through XLA").

Differences from the XENT families, per Gulrajani et al. 2017:
- the critic ends in a LINEAR score (no sigmoid), loss = E[D(fake)] − E[D(real)];
- no BatchNorm in the critic (GP is defined per-example; batch statistics
  couple examples), so the critic is conv/dense only;
- critic trains ``n_critic`` steps per generator step;
- the penalty λ·E[(‖∇_x̂ D(x̂)‖−1)²] differentiates *through* the critic's
  input gradient — ``jax.grad`` composed over ``jax.grad``, which XLA lowers
  natively (ops/losses.py::gradient_penalty).

The trainer fuses each critic round (n_critic steps, lax.scan) and the
generator step into single jitted programs, donated, mesh-shardable over the
``data`` axis — the same execution shape as the fused DCGAN iteration."""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.nn import (
    BatchNormalization,
    ComputationGraph,
    ConvolutionLayer,
    Deconvolution2D,
    DenseLayer,
    FeedForwardToCnnPreProcessor,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.optim import Adam
from gan_deeplearning4j_tpu.optim.optimizer import GraphOptimizer
from gan_deeplearning4j_tpu.ops import losses as loss_ops
from gan_deeplearning4j_tpu.parallel.trainer import TrainState, make_train_state


@dataclasses.dataclass(frozen=True)
class WganGpConfig:
    height: int = 32
    width: int = 32
    channels: int = 3
    z_size: int = 128
    base_filters: int = 64
    dense_width: int = 1024
    critic_learning_rate: float = 2e-4
    gen_learning_rate: float = 2e-4
    # Adam(β1=0, β2=0.9) per Gulrajani et al. 2017 §5 — the BASELINE.json
    # north star names Adam; WGAN-GP is the config that genuinely uses it
    adam_beta1: float = 0.0
    adam_beta2: float = 0.9
    gp_lambda: float = 10.0
    n_critic: int = 5
    seed: int = 666
    grad_clip: float = 0.0  # WGAN-GP needs no clipping; GP regularizes

    @property
    def num_features(self) -> int:
        return self.height * self.width * self.channels

    @property
    def stages(self) -> int:
        from gan_deeplearning4j_tpu.models.dcgan_image import stages_for

        return stages_for(self.height, self.width)


def _updater(cfg: WganGpConfig, lr: float) -> Adam:
    return Adam(lr, cfg.adam_beta1, cfg.adam_beta2, 1e-8)


def _graph_config(cfg: WganGpConfig, lr: float) -> GraphConfig:
    return GraphConfig(
        seed=cfg.seed,
        default_activation="leaky_relu",
        weight_init="xavier",
        l2=0.0,
        gradient_clip=None if cfg.grad_clip <= 0 else "elementwise",
        gradient_clip_value=cfg.grad_clip,
        updater=_updater(cfg, lr),
        optimization_algo="sgd",
    )


def build_critic(cfg: WganGpConfig = WganGpConfig()) -> ComputationGraph:
    """Conv critic, NO BatchNorm, linear score head (loss='wasserstein')."""
    up = _updater(cfg, cfg.critic_learning_rate)
    b = GraphBuilder(_graph_config(cfg, cfg.critic_learning_rate))
    b.add_inputs("critic_input_0")
    b.set_input_types(InputType.convolutional_flat(cfg.height, cfg.width, cfg.channels))
    prev = "critic_input_0"
    n_in, filters = cfg.channels, cfg.base_filters
    for i in range(cfg.stages):
        name = f"critic_conv2d_{i + 1}"
        b.add_layer(
            name,
            ConvolutionLayer(kernel=5, stride=2, padding=2, n_in=n_in, n_out=filters, updater=up),
            prev,
        )
        prev = name
        n_in, filters = filters, filters * 2
    b.add_layer("critic_dense", DenseLayer(n_out=cfg.dense_width, updater=up), prev)
    b.add_layer(
        "critic_score",
        OutputLayer(n_out=1, activation="identity", loss="wasserstein", updater=up),
        "critic_dense",
    )
    b.set_outputs("critic_score")
    return b.build()


def build_generator(cfg: WganGpConfig = WganGpConfig()) -> ComputationGraph:
    """z → dense stem → deconv ×2 stages → sigmoid image, BN allowed here."""
    up = _updater(cfg, cfg.gen_learning_rate)
    stem_c = cfg.base_filters * (2 ** (cfg.stages - 1))
    b = GraphBuilder(_graph_config(cfg, cfg.gen_learning_rate))
    b.add_inputs("gen_input_0")
    b.set_input_types(InputType.feed_forward(cfg.z_size))
    b.add_layer("gen_dense_1", DenseLayer(n_out=4 * 4 * stem_c, updater=up), "gen_input_0")
    b.add_layer("gen_batch_2", BatchNormalization(updater=up), "gen_dense_1")
    prev = "gen_batch_2"
    pre = FeedForwardToCnnPreProcessor(4, 4, stem_c)
    c = stem_c
    for s in range(cfg.stages):
        n_out = max(cfg.base_filters // 2, c // 2)
        name = f"gen_deconv2d_{3 + s}"
        b.add_layer(
            name,
            Deconvolution2D(kernel=4, stride=2, padding=1, n_in=c, n_out=n_out, updater=up),
            prev,
            preprocessor=pre if s == 0 else None,
        )
        prev = name
        c = n_out
    b.add_layer(
        "gen_image",
        ConvolutionLayer(kernel=5, stride=1, padding=2, n_in=c, n_out=cfg.channels,
                         activation="sigmoid", updater=up),
        prev,
    )
    b.set_outputs("gen_image")
    return b.build()


class WganGpTrainer:
    """Alternating WGAN-GP training: one fused critic round (n_critic scanned
    steps) + one fused generator step, both jitted with donation."""

    def __init__(
        self,
        cfg: WganGpConfig = WganGpConfig(),
        mesh: Optional[jax.sharding.Mesh] = None,
        data_axis: str = "data",
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.data_axis = data_axis
        self.critic = build_critic(cfg)
        self.generator = build_generator(cfg)
        self.critic_opt = GraphOptimizer(self.critic)
        self.gen_opt = GraphOptimizer(self.generator)
        self._critic_round = self._build_critic_round()
        self._gen_step = self._build_gen_step()

    # -- state --------------------------------------------------------------
    def init_states(self, seed: Optional[int] = None) -> Tuple[TrainState, TrainState]:
        critic = make_train_state(self.critic, self.critic_opt, self.mesh, seed)
        gen = make_train_state(self.generator, self.gen_opt, self.mesh, seed)
        return critic, gen

    def _shardings(self):
        if self.mesh is None:
            return {}
        rep = jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec())
        data = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec(self.data_axis)
        )
        return {"rep": rep, "data": data}

    def _critic_loss(self, cparams, gen_params, real, rng):
        """E[D(fake)] − E[D(real)] + λ·GP. Flat (N, F) in/out — the critic
        graph's flat→cnn adapter reshapes internally, so the GP's input
        gradient is taken w.r.t. the flat pixels (norm is reshape-invariant)."""
        b = real.shape[0]
        k_z, k_gp = jax.random.split(rng)
        z = jax.random.normal(k_z, (b, self.cfg.z_size), real.dtype)
        fake = self.generator.output(gen_params, z, train=False)
        fake = fake.reshape(b, -1)

        def score(x):
            return self.critic.output(cparams, x, train=False)[:, 0]

        w_loss = jnp.mean(score(fake)) - jnp.mean(score(real))
        gp = loss_ops.gradient_penalty(score, real, fake, k_gp)
        return w_loss + self.cfg.gp_lambda * gp

    def _build_critic_round(self):
        def round_fn(critic_state: TrainState, gen_params, real_batches, rng):
            """real_batches: (n_critic, B, F) — one critic step per slice."""

            def body(carry, inputs):
                params, opt_state, key = carry
                real = inputs
                key, sub = jax.random.split(key)
                loss, grads = jax.value_and_grad(self._critic_loss)(
                    params, gen_params, real, sub
                )
                params, opt_state = self.critic_opt.step(params, grads, opt_state)
                return (params, opt_state, key), loss

            (params, opt_state, _), losses = jax.lax.scan(
                body, (critic_state.params, critic_state.opt_state, rng), real_batches
            )
            new_state = TrainState(
                params, opt_state, critic_state.step + real_batches.shape[0]
            )
            return new_state, jnp.mean(losses)

        self._round_body = round_fn  # traceable body, reused by _build_multi_round
        kwargs = {"donate_argnums": (0,)}
        sh = self._shardings()
        if sh:
            # scan axis replicated, batch axis sharded
            batches = jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec(None, self.data_axis)
            )
            kwargs["in_shardings"] = (sh["rep"], sh["rep"], batches, sh["rep"])
            kwargs["out_shardings"] = (sh["rep"], sh["rep"])
        return jax.jit(round_fn, **kwargs)

    def _build_gen_step(self):
        def gen_loss(gparams, cparams, z):
            outs, new_params = self.generator.apply(gparams, z, train=True)
            fake = outs["gen_image"].reshape(z.shape[0], -1)
            loss = -jnp.mean(self.critic.output(cparams, fake, train=False)[:, 0])
            return loss, new_params  # new_params carries BN running stats

        def step(gen_state: TrainState, critic_params, z):
            (loss, new_params), grads = jax.value_and_grad(gen_loss, has_aux=True)(
                gen_state.params, critic_params, z
            )
            params, opt_state = self.gen_opt.step(new_params, grads, gen_state.opt_state)
            return TrainState(params, opt_state, gen_state.step + 1), loss

        self._gen_body = step  # traceable body, reused by _build_multi_round
        kwargs = {"donate_argnums": (0,)}
        sh = self._shardings()
        if sh:
            kwargs["in_shardings"] = (sh["rep"], sh["rep"], sh["data"])
            kwargs["out_shardings"] = (sh["rep"], sh["rep"])
        return jax.jit(step, **kwargs)

    def _build_multi_round(self):
        """K full WGAN-GP rounds (n_critic critic steps + one generator step
        each) as ONE scanned XLA program — the device training loop, same
        shape as GanExperiment.train_iterations (round-3 perf work: each
        dispatch through a tunneled chip costs milliseconds, so the host
        feeds round WINDOWS instead of rounds)."""
        round_body = self._round_body
        gen_body = self._gen_body

        def multi(critic_state, gen_state, rounds, rng):
            """rounds: (K, n_critic, B, F); rng: one key for the window."""

            def body(carry, xs):
                cs, gs = carry
                real_batches, key = xs
                k_c, k_g = jax.random.split(key)
                cs, c_loss = round_body(cs, gs.params, real_batches, k_c)
                z = jax.random.normal(
                    k_g, (real_batches.shape[1], self.cfg.z_size), real_batches.dtype
                )
                gs, g_loss = gen_body(gs, cs.params, z)
                return (cs, gs), (c_loss, g_loss)

            keys = jax.random.split(rng, rounds.shape[0])
            (cs, gs), (c_losses, g_losses) = jax.lax.scan(
                body, (critic_state, gen_state), (rounds, keys)
            )
            return cs, gs, c_losses, g_losses

        kwargs = {"donate_argnums": (0, 1)}
        sh = self._shardings()
        if sh:
            rounds_sh = jax.sharding.NamedSharding(
                self.mesh,
                jax.sharding.PartitionSpec(None, None, self.data_axis),
            )
            kwargs["in_shardings"] = (sh["rep"], sh["rep"], rounds_sh, sh["rep"])
            kwargs["out_shardings"] = (sh["rep"],) * 4
        return jax.jit(multi, **kwargs)

    # -- public steps -------------------------------------------------------
    def train_round(
        self, critic_state: TrainState, gen_state: TrainState, real_batches, rng
    ):
        """One WGAN-GP round: n_critic critic steps then one generator step.
        ``real_batches`` is (n_critic, B, num_features) float32 in [0,1]."""
        real_batches = jnp.asarray(real_batches)
        if real_batches.shape[0] != self.cfg.n_critic:
            raise ValueError(
                f"need {self.cfg.n_critic} critic batches, got {real_batches.shape[0]}"
            )
        k_c, k_g = jax.random.split(jnp.asarray(rng))
        critic_state, c_loss = self._critic_round(
            critic_state, gen_state.params, real_batches, k_c
        )
        z = jax.random.normal(
            k_g, (real_batches.shape[1], self.cfg.z_size), real_batches.dtype
        )
        gen_state, g_loss = self._gen_step(gen_state, critic_state.params, z)
        return critic_state, gen_state, c_loss, g_loss

    def train_rounds(self, critic_state, gen_state, rounds, rng):
        """K rounds in one dispatch. ``rounds``: (K, n_critic, B, features).
        Returns (critic_state, gen_state, c_losses (K,), g_losses (K,)) —
        losses stay on device. Per-round RNG derives from one window key
        (split K ways), vs ``train_round``'s one host split per call —
        statistically equivalent streams, not bit-identical ones."""
        rounds = jnp.asarray(rounds)
        if rounds.ndim != 4 or rounds.shape[1] != self.cfg.n_critic:
            raise ValueError(
                f"rounds must be (K, n_critic={self.cfg.n_critic}, B, F); "
                f"got {rounds.shape}"
            )
        if getattr(self, "_multi_round", None) is None:
            self._multi_round = self._build_multi_round()
        return self._multi_round(critic_state, gen_state, rounds, jnp.asarray(rng))

    def sample(self, gen_state: TrainState, rng, num: int):
        """Generate ``num`` images (num, H, W, C) for eval/FID."""
        z = jax.random.normal(jnp.asarray(rng), (num, self.cfg.z_size), jnp.float32)
        return self.generator.output(gen_state.params, z, train=False)
