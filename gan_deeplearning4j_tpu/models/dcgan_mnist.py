"""The reference's DCGAN-MNIST model family — three graphs + the transfer
classifier, with the reference's exact layer names, topology and hyperparameters
(dl4jGANComputerVision.java:117-314,335-368), built on this framework's
TPU-native graph system.

Architecture parity notes:
- Per-layer RmsProp(lr, 1e-8, 1e-8) exactly as the reference attaches them;
  "frozen" layers use LR 0.0 (:84).
- Layer names match the reference string-for-string because the weight-sync
  protocol (:429-542) addresses parameters by (layer, name); the sync mappings
  below are the same copies expressed as bulk ``copy_params`` maps.
- ``gen_deconv2d_5``/``gen_deconv2d_7`` are Upsampling2D layers (the reference
  names them deconv but builds Upsampling2D, :201-206,210-214).
- The dis graph declares ``InputType.convolutionalFlat(28,28,1)`` (:165);
  batch/conv layers see NHWC activations via the automatic flat→cnn adapter.
"""

from __future__ import annotations

import dataclasses

from gan_deeplearning4j_tpu.nn import (
    BatchNormalization,
    ComputationGraph,
    ConvolutionLayer,
    DenseLayer,
    FeedForwardToCnnPreProcessor,
    FineTuneConfiguration,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
    SubsamplingLayer,
    TransferLearning,
    Upsampling2D,
)
from gan_deeplearning4j_tpu.optim import RmsProp


@dataclasses.dataclass(frozen=True)
class DcganConfig:
    """The reference's hyperparameter block (dl4jGANComputerVision.java:66-92),
    model-side subset."""

    height: int = 28
    width: int = 28
    channels: int = 1
    num_features: int = 784
    num_classes: int = 10
    num_classes_dis: int = 1
    z_size: int = 2
    dis_learning_rate: float = 0.002
    gen_learning_rate: float = 0.004
    frozen_learning_rate: float = 0.0
    seed: int = 666  # numberOfTheBeast
    l2: float = 1e-4
    grad_clip: float = 1.0


def _graph_config(cfg: DcganConfig) -> GraphConfig:
    # common block of every reference graph (:119-129)
    return GraphConfig(
        seed=cfg.seed,
        default_activation="tanh",
        weight_init="xavier",
        l2=cfg.l2,
        gradient_clip="elementwise",
        gradient_clip_value=cfg.grad_clip,
        updater=RmsProp(cfg.dis_learning_rate, 1e-8, 1e-8),
        optimization_algo="sgd",
    )


def _add_discriminator_layers(
    b: GraphBuilder, prefix: str, start: int, lr: float, cfg: DcganConfig, input_name: str
) -> str:
    """The 7-layer discriminator stack shared by ``dis`` (names
    ``dis_*_layer_1..7``, dl4jGANComputerVision.java:132-163) and the frozen
    tail of ``gan`` (``gan_dis_*_layer_9..15``, :276-308). One definition keeps
    the two copies structurally identical, which the DIS_TO_GAN weight-sync
    protocol depends on. Returns the output-layer name."""
    up = RmsProp(lr, 1e-8, 1e-8)
    names = [f"{prefix}_{kind}_layer_{start + i}" for i, kind in enumerate(
        ["batch", "conv2d", "maxpool", "conv2d", "maxpool", "dense", "output"]
    )]
    b.add_layer(names[0], BatchNormalization(updater=up), input_name)
    b.add_layer(
        names[1],
        ConvolutionLayer(kernel=5, stride=2, n_in=cfg.channels, n_out=64, updater=up),
        names[0],
    )
    b.add_layer(names[2], SubsamplingLayer(pool="max", kernel=2, stride=1), names[1])
    b.add_layer(
        names[3],
        ConvolutionLayer(kernel=5, stride=2, n_in=64, n_out=128, updater=up),
        names[2],
    )
    b.add_layer(names[4], SubsamplingLayer(pool="max", kernel=2, stride=1), names[3])
    b.add_layer(names[5], DenseLayer(n_out=1024, updater=up), names[4])
    b.add_layer(
        names[6],
        OutputLayer(n_out=cfg.num_classes_dis, activation="sigmoid", loss="xent", updater=up),
        names[5],
    )
    return names[6]


def build_discriminator(cfg: DcganConfig = DcganConfig()) -> ComputationGraph:
    """Trainable discriminator ``dis`` (dl4jGANComputerVision.java:118-166)."""
    b = GraphBuilder(_graph_config(cfg))
    b.add_inputs("dis_input_layer_0")
    b.set_input_types(InputType.convolutional_flat(cfg.height, cfg.width, cfg.channels))
    out = _add_discriminator_layers(
        b, "dis", 1, cfg.dis_learning_rate, cfg, "dis_input_layer_0"
    )
    b.set_outputs(out)
    return b.build()


def _add_generator_layers(b: GraphBuilder, prefix: str, lr: float, cfg: DcganConfig, input_name: str) -> str:
    """The 8-layer generator stack shared by ``gen`` (frozen LR) and ``gan``
    (LR 0.004) — dl4jGANComputerVision.java:186-220 vs :240-274. Layer names
    keep the reference's ``{prefix}_...`` scheme; returns the output name."""
    up = RmsProp(lr, 1e-8, 1e-8)
    dense3 = 7 * 7 * 128
    b.add_layer(f"{prefix}_batch_1", BatchNormalization(updater=up), input_name)
    b.add_layer(f"{prefix}_dense_layer_2", DenseLayer(n_out=1024, updater=up), f"{prefix}_batch_1")
    b.add_layer(
        f"{prefix}_dense_layer_3", DenseLayer(n_out=dense3, updater=up), f"{prefix}_dense_layer_2"
    )
    b.add_layer(f"{prefix}_batch_4", BatchNormalization(updater=up), f"{prefix}_dense_layer_3")
    b.add_layer(
        f"{prefix}_deconv2d_5",
        Upsampling2D(size=2),
        f"{prefix}_batch_4",
        preprocessor=FeedForwardToCnnPreProcessor(7, 7, 128),
    )
    b.add_layer(
        f"{prefix}_conv2d_6",
        ConvolutionLayer(kernel=5, stride=1, padding=2, n_in=128, n_out=64, updater=up),
        f"{prefix}_deconv2d_5",
    )
    b.add_layer(f"{prefix}_deconv2d_7", Upsampling2D(size=2), f"{prefix}_conv2d_6")
    b.add_layer(
        f"{prefix}_conv2d_8",
        ConvolutionLayer(
            kernel=5, stride=1, padding=2, n_in=64, n_out=cfg.channels,
            activation="sigmoid", updater=up,
        ),
        f"{prefix}_deconv2d_7",
    )
    return f"{prefix}_conv2d_8"


def build_generator(cfg: DcganConfig = DcganConfig()) -> ComputationGraph:
    """Frozen sampler ``gen`` — all updaters LR 0.0; weights refreshed by
    copying from ``gan`` (dl4jGANComputerVision.java:172-225)."""
    b = GraphBuilder(_graph_config(cfg))
    b.add_inputs("gen_input_layer_0")
    b.set_input_types(InputType.feed_forward(cfg.z_size))
    out = _add_generator_layers(b, "gen", cfg.frozen_learning_rate, cfg, "gen_input_layer_0")
    b.set_outputs(out)
    return b.build()


def build_gan(cfg: DcganConfig = DcganConfig()) -> ComputationGraph:
    """Stacked GAN: trainable generator (LR 0.004) feeding a frozen
    discriminator copy (LR 0.0), one XENT loss at the end so generator
    gradients flow through the frozen D (dl4jGANComputerVision.java:227-314)."""
    b = GraphBuilder(_graph_config(cfg))
    b.add_inputs("gan_input_layer_0")
    b.set_input_types(InputType.feed_forward(cfg.z_size))
    gen_out = _add_generator_layers(b, "gan", cfg.gen_learning_rate, cfg, "gan_input_layer_0")
    out = _add_discriminator_layers(
        b, "gan_dis", 9, cfg.frozen_learning_rate, cfg, gen_out
    )
    b.set_outputs(out)
    return b.build()


def build_transfer_classifier(dis_graph: ComputationGraph, dis_params, cfg: DcganConfig = DcganConfig()):
    """The ``computerVision`` classifier: dis features frozen below
    ``dis_dense_layer_6``, old sigmoid head replaced by BatchNorm(1024) +
    Softmax(10) under MCXENT (dl4jGANComputerVision.java:335-368). The new
    output head reuses the name ``dis_output_layer_7`` as the reference does."""
    up = RmsProp(cfg.dis_learning_rate, 1e-8, 1e-8)
    return (
        TransferLearning(dis_graph, dis_params)
        .fine_tune_configuration(
            FineTuneConfiguration(
                seed=cfg.seed,
                default_activation="tanh",
                weight_init="xavier",
                l2=cfg.l2,
                gradient_clip="elementwise",
                gradient_clip_value=cfg.grad_clip,
                updater=up,
                optimization_algo="sgd",
            )
        )
        .set_feature_extractor("dis_dense_layer_6")
        .remove_vertex_keep_connections("dis_output_layer_7")
        .add_layer("dis_batch", BatchNormalization(updater=up), "dis_dense_layer_6")
        .add_layer(
            "dis_output_layer_7",
            OutputLayer(n_out=cfg.num_classes, activation="softmax", loss="mcxent", updater=up),
            "dis_batch",
        )
        .build()
    )


# --- weight-sync protocol (dl4jGANComputerVision.java:429-542) -------------
# dis → gan frozen tail: refresh the stacked GAN's discriminator copy after a
# dis step (12 named-param copies in the reference; here one bulk map).
DIS_TO_GAN = {
    "dis_batch_layer_1": "gan_dis_batch_layer_9",
    "dis_conv2d_layer_2": "gan_dis_conv2d_layer_10",
    "dis_conv2d_layer_4": "gan_dis_conv2d_layer_12",
    "dis_dense_layer_6": "gan_dis_dense_layer_14",
    "dis_output_layer_7": "gan_dis_output_layer_15",
}

# gan → gen: refresh the frozen sampler after a generator step (16 copies).
GAN_TO_GEN = {
    "gan_batch_1": "gen_batch_1",
    "gan_dense_layer_2": "gen_dense_layer_2",
    "gan_dense_layer_3": "gen_dense_layer_3",
    "gan_batch_4": "gen_batch_4",
    "gan_conv2d_6": "gen_conv2d_6",
    "gan_conv2d_8": "gen_conv2d_8",
}

# dis → classifier feature layers (10 copies; head layers excluded).
DIS_TO_CV = {
    "dis_batch_layer_1": "dis_batch_layer_1",
    "dis_conv2d_layer_2": "dis_conv2d_layer_2",
    "dis_conv2d_layer_4": "dis_conv2d_layer_4",
    "dis_dense_layer_6": "dis_dense_layer_6",
}
