"""Multi-resolution image DCGANs — BASELINE.md configs 3 and 4:
CIFAR-10 32×32×3 and CelebA 64×64×3 (data-parallel).

Same three-graph + weight-sync architecture as the MNIST family
(dcgan_mnist.py; reference topology dl4jGANComputerVision.java:117-314),
generalized over resolution/channels. The generator uses Deconvolution2D
(k4 s2 p1 — exact ×2 per stage) instead of the MNIST family's
Upsampling2D+Conv pair, exercising the transposed-conv path of the op layer
("Conv/Deconv + BatchNorm", BASELINE.md). Stages are log2(side/4), so 32×32
runs 3 deconv stages and 64×64 runs 4.

Includes a deterministic synthetic image source (no network egress in this
environment) shaped like the real datasets."""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import numpy as np

from gan_deeplearning4j_tpu.nn import (
    BatchNormalization,
    ComputationGraph,
    ConvolutionLayer,
    Deconvolution2D,
    DenseLayer,
    FeedForwardToCnnPreProcessor,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
    SubsamplingLayer,
)
from gan_deeplearning4j_tpu.optim import RmsProp


def stages_for(height: int, width: int) -> int:
    """Deconv/conv stages between a 4×4 stem and full resolution — the shared
    resolution contract of the image GAN families (also wgan_gp)."""
    if height != width or height < 8 or height & (height - 1):
        raise ValueError(f"side must be a power of two >= 8, got {height}x{width}")
    return int(np.log2(height // 4))


@dataclasses.dataclass(frozen=True)
class ImageGanConfig:
    height: int = 32
    width: int = 32
    channels: int = 3
    z_size: int = 64
    base_filters: int = 64  # discriminator stage-1 width; doubles per stage
    dense_width: int = 1024
    dis_learning_rate: float = 0.002
    gen_learning_rate: float = 0.004
    frozen_learning_rate: float = 0.0
    seed: int = 666
    l2: float = 1e-4
    grad_clip: float = 1.0

    @property
    def num_features(self) -> int:
        return self.height * self.width * self.channels

    @property
    def stages(self) -> int:
        return stages_for(self.height, self.width)


CIFAR10 = ImageGanConfig(height=32, width=32, channels=3)
CELEBA64 = ImageGanConfig(height=64, width=64, channels=3)


def _graph_config(cfg: ImageGanConfig) -> GraphConfig:
    return GraphConfig(
        seed=cfg.seed,
        default_activation="tanh",
        weight_init="xavier",
        l2=cfg.l2,
        gradient_clip="elementwise",
        gradient_clip_value=cfg.grad_clip,
        updater=RmsProp(cfg.dis_learning_rate, 1e-8, 1e-8),
        optimization_algo="sgd",
    )


def _add_discriminator_layers(
    b: GraphBuilder, prefix: str, start: int, lr: float, cfg: ImageGanConfig, input_name: str
) -> str:
    """BN stem, then per stage: conv5 s2 (halving) + maxpool 2 s1 (the MNIST
    family's conv/pool rhythm, dl4jGANComputerVision.java:132-154), then
    dense + sigmoid XENT head. Returns the output-layer name."""
    up = RmsProp(lr, 1e-8, 1e-8)
    i = start
    b.add_layer(f"{prefix}_batch_layer_{i}", BatchNormalization(updater=up), input_name)
    prev = f"{prefix}_batch_layer_{i}"
    i += 1
    n_in = cfg.channels
    filters = cfg.base_filters
    for _ in range(cfg.stages):
        b.add_layer(
            f"{prefix}_conv2d_layer_{i}",
            ConvolutionLayer(kernel=5, stride=2, padding=2, n_in=n_in, n_out=filters, updater=up),
            prev,
        )
        prev = f"{prefix}_conv2d_layer_{i}"
        i += 1
        b.add_layer(
            f"{prefix}_maxpool_layer_{i}",
            SubsamplingLayer(pool="max", kernel=2, stride=1),
            prev,
        )
        prev = f"{prefix}_maxpool_layer_{i}"
        i += 1
        n_in, filters = filters, filters * 2
    b.add_layer(f"{prefix}_dense_layer_{i}", DenseLayer(n_out=cfg.dense_width, updater=up), prev)
    prev = f"{prefix}_dense_layer_{i}"
    i += 1
    out = f"{prefix}_output_layer_{i}"
    b.add_layer(out, OutputLayer(n_out=1, activation="sigmoid", loss="xent", updater=up), prev)
    return out


def _add_generator_layers(
    b: GraphBuilder, prefix: str, lr: float, cfg: ImageGanConfig, input_name: str
) -> str:
    """z → BN → dense → dense(4·4·C₀) → BN → reshape → per stage: deconv
    k4 s2 p1 (exact ×2) → final conv5 p2 to ``channels`` with sigmoid."""
    up = RmsProp(lr, 1e-8, 1e-8)
    stem_c = cfg.base_filters * (2 ** (cfg.stages - 1))
    b.add_layer(f"{prefix}_batch_1", BatchNormalization(updater=up), input_name)
    b.add_layer(f"{prefix}_dense_layer_2", DenseLayer(n_out=cfg.dense_width, updater=up), f"{prefix}_batch_1")
    b.add_layer(
        f"{prefix}_dense_layer_3",
        DenseLayer(n_out=4 * 4 * stem_c, updater=up),
        f"{prefix}_dense_layer_2",
    )
    b.add_layer(f"{prefix}_batch_4", BatchNormalization(updater=up), f"{prefix}_dense_layer_3")
    prev = f"{prefix}_batch_4"
    i = 5
    c = stem_c
    pre = FeedForwardToCnnPreProcessor(4, 4, stem_c)
    for s in range(cfg.stages):
        n_out = max(cfg.base_filters // 2, c // 2)
        b.add_layer(
            f"{prefix}_deconv2d_{i}",
            Deconvolution2D(kernel=4, stride=2, padding=1, n_in=c, n_out=n_out, updater=up),
            prev,
            preprocessor=pre if s == 0 else None,
        )
        prev = f"{prefix}_deconv2d_{i}"
        i += 1
        c = n_out
    out = f"{prefix}_conv2d_{i}"
    b.add_layer(
        out,
        ConvolutionLayer(
            kernel=5, stride=1, padding=2, n_in=c, n_out=cfg.channels,
            activation="sigmoid", updater=up,
        ),
        prev,
    )
    return out


def build_discriminator(cfg: ImageGanConfig = CIFAR10) -> ComputationGraph:
    b = GraphBuilder(_graph_config(cfg))
    b.add_inputs("dis_input_layer_0")
    b.set_input_types(InputType.convolutional_flat(cfg.height, cfg.width, cfg.channels))
    out = _add_discriminator_layers(b, "dis", 1, cfg.dis_learning_rate, cfg, "dis_input_layer_0")
    b.set_outputs(out)
    return b.build()


def build_generator(cfg: ImageGanConfig = CIFAR10) -> ComputationGraph:
    b = GraphBuilder(_graph_config(cfg))
    b.add_inputs("gen_input_layer_0")
    b.set_input_types(InputType.feed_forward(cfg.z_size))
    out = _add_generator_layers(b, "gen", cfg.frozen_learning_rate, cfg, "gen_input_layer_0")
    b.set_outputs(out)
    return b.build()


def build_gan(cfg: ImageGanConfig = CIFAR10) -> ComputationGraph:
    b = GraphBuilder(_graph_config(cfg))
    b.add_inputs("gan_input_layer_0")
    b.set_input_types(InputType.feed_forward(cfg.z_size))
    gen_out = _add_generator_layers(b, "gan", cfg.gen_learning_rate, cfg, "gan_input_layer_0")
    start = 5 + cfg.stages + 1  # first index after the generator stack
    out = _add_discriminator_layers(b, "gan_dis", start, cfg.frozen_learning_rate, cfg, gen_out)
    b.set_outputs(out)
    return b.build()


def sync_maps(cfg: ImageGanConfig = CIFAR10) -> Tuple[Dict[str, str], Dict[str, str]]:
    """(DIS_TO_GAN, GAN_TO_GEN) weight-sync maps, mirroring
    dcgan_mnist.DIS_TO_GAN / GAN_TO_GEN for this topology."""
    start = 5 + cfg.stages + 1
    dis_to_gan = {"dis_batch_layer_1": f"gan_dis_batch_layer_{start}"}
    i_src, i_dst = 2, start + 1
    for _ in range(cfg.stages):
        dis_to_gan[f"dis_conv2d_layer_{i_src}"] = f"gan_dis_conv2d_layer_{i_dst}"
        i_src += 2  # skip the param-free maxpool
        i_dst += 2
    dis_to_gan[f"dis_dense_layer_{i_src}"] = f"gan_dis_dense_layer_{i_dst}"
    dis_to_gan[f"dis_output_layer_{i_src + 1}"] = f"gan_dis_output_layer_{i_dst + 1}"

    gan_to_gen = {
        "gan_batch_1": "gen_batch_1",
        "gan_dense_layer_2": "gen_dense_layer_2",
        "gan_dense_layer_3": "gen_dense_layer_3",
        "gan_batch_4": "gen_batch_4",
    }
    for k in range(cfg.stages):
        gan_to_gen[f"gan_deconv2d_{5 + k}"] = f"gen_deconv2d_{5 + k}"
    gan_to_gen[f"gan_conv2d_{5 + cfg.stages}"] = f"gen_conv2d_{5 + cfg.stages}"
    return dis_to_gan, gan_to_gen


def synthetic_images(
    num: int, cfg: ImageGanConfig = CIFAR10, seed: int = 666
) -> np.ndarray:
    """Deterministic CIFAR/CelebA-shaped samples, (N, H·W·C) float32 in [0,1]:
    per-class smooth color fields with object-like blobs — structured enough
    for train/eval smoke runs without real data (no egress here)."""
    rng = np.random.default_rng(seed)
    h, w, c = cfg.height, cfg.width, cfg.channels
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    yy, xx = yy / h, xx / w
    out = np.empty((num, h, w, c), dtype=np.float32)
    for i in range(num):
        img = np.empty((h, w, c), dtype=np.float32)
        cy, cx = rng.uniform(0.3, 0.7, size=2)
        r = rng.uniform(0.1, 0.3)
        blob = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * r * r)))
        for ch in range(c):
            fx, fy = rng.uniform(0.5, 2.0, size=2)
            px, py = rng.uniform(0, 2 * np.pi, size=2)
            bg = 0.5 + 0.25 * np.cos(2 * np.pi * fx * xx + px) * np.cos(
                2 * np.pi * fy * yy + py
            )
            img[:, :, ch] = bg + rng.uniform(-0.4, 0.4) * blob
        img += rng.normal(0, 0.03, size=img.shape)
        out[i] = np.clip(img, 0.0, 1.0)
    return out.reshape(num, cfg.num_features)
