"""Tabular MLP-GAN — BASELINE.md config 2: Dense-only G/D on synthetic
financial-transactions data.

Same framework surface as the DCGAN family (named layers, per-layer RmsProp,
LR-0 freezing, the three-graph + weight-sync protocol of
dl4jGANComputerVision.java:408-548), but the convolutional stack is replaced
by dense layers — tabular rows have no spatial structure. Layer naming keeps
the reference's ``{prefix}_{kind}_layer_{i}`` scheme so the sync maps and
checkpoint format work identically."""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

from gan_deeplearning4j_tpu.nn import (
    BatchNormalization,
    ComputationGraph,
    DenseLayer,
    GraphBuilder,
    GraphConfig,
    InputType,
    OutputLayer,
)
from gan_deeplearning4j_tpu.optim import RmsProp


@dataclasses.dataclass(frozen=True)
class MlpGanConfig:
    """Hyperparameters, reference-style (dl4jGANComputerVision.java:66-92
    values where they transfer; dense widths sized for tabular rows)."""

    num_features: int = 32
    z_size: int = 8
    hidden: Tuple[int, ...] = (256, 256)
    dis_learning_rate: float = 0.002
    gen_learning_rate: float = 0.004
    frozen_learning_rate: float = 0.0
    seed: int = 666
    l2: float = 1e-4
    grad_clip: float = 1.0


def _graph_config(cfg: MlpGanConfig) -> GraphConfig:
    return GraphConfig(
        seed=cfg.seed,
        default_activation="tanh",
        weight_init="xavier",
        l2=cfg.l2,
        gradient_clip="elementwise",
        gradient_clip_value=cfg.grad_clip,
        updater=RmsProp(cfg.dis_learning_rate, 1e-8, 1e-8),
        optimization_algo="sgd",
    )


def _add_discriminator_layers(
    b: GraphBuilder, prefix: str, start: int, lr: float, cfg: MlpGanConfig, input_name: str
) -> str:
    up = RmsProp(lr, 1e-8, 1e-8)
    prev = input_name
    i = start
    b.add_layer(f"{prefix}_batch_layer_{i}", BatchNormalization(updater=up), prev)
    prev = f"{prefix}_batch_layer_{i}"
    i += 1
    for width in cfg.hidden:
        b.add_layer(f"{prefix}_dense_layer_{i}", DenseLayer(n_out=width, updater=up), prev)
        prev = f"{prefix}_dense_layer_{i}"
        i += 1
    out = f"{prefix}_output_layer_{i}"
    b.add_layer(
        out, OutputLayer(n_out=1, activation="sigmoid", loss="xent", updater=up), prev
    )
    return out


def _add_generator_layers(
    b: GraphBuilder, prefix: str, lr: float, cfg: MlpGanConfig, input_name: str
) -> str:
    up = RmsProp(lr, 1e-8, 1e-8)
    b.add_layer(f"{prefix}_batch_1", BatchNormalization(updater=up), input_name)
    prev = f"{prefix}_batch_1"
    i = 2
    for width in cfg.hidden:
        b.add_layer(f"{prefix}_dense_layer_{i}", DenseLayer(n_out=width, updater=up), prev)
        prev = f"{prefix}_dense_layer_{i}"
        i += 1
    out = f"{prefix}_dense_layer_{i}"
    # sigmoid output keeps generated rows in [0,1] like the scaled real data
    b.add_layer(
        out, DenseLayer(n_out=cfg.num_features, activation="sigmoid", updater=up), prev
    )
    return out


def build_discriminator(cfg: MlpGanConfig = MlpGanConfig()) -> ComputationGraph:
    b = GraphBuilder(_graph_config(cfg))
    b.add_inputs("dis_input_layer_0")
    b.set_input_types(InputType.feed_forward(cfg.num_features))
    out = _add_discriminator_layers(b, "dis", 1, cfg.dis_learning_rate, cfg, "dis_input_layer_0")
    b.set_outputs(out)
    return b.build()


def build_generator(cfg: MlpGanConfig = MlpGanConfig()) -> ComputationGraph:
    b = GraphBuilder(_graph_config(cfg))
    b.add_inputs("gen_input_layer_0")
    b.set_input_types(InputType.feed_forward(cfg.z_size))
    out = _add_generator_layers(b, "gen", cfg.frozen_learning_rate, cfg, "gen_input_layer_0")
    b.set_outputs(out)
    return b.build()


def build_gan(cfg: MlpGanConfig = MlpGanConfig()) -> ComputationGraph:
    b = GraphBuilder(_graph_config(cfg))
    b.add_inputs("gan_input_layer_0")
    b.set_input_types(InputType.feed_forward(cfg.z_size))
    gen_out = _add_generator_layers(b, "gan", cfg.gen_learning_rate, cfg, "gan_input_layer_0")
    start = 2 + len(cfg.hidden) + 1  # first index after the generator stack
    out = _add_discriminator_layers(
        b, "gan_dis", start, cfg.frozen_learning_rate, cfg, gen_out
    )
    b.set_outputs(out)
    return b.build()


def sync_maps(cfg: MlpGanConfig = MlpGanConfig()):
    """(DIS_TO_GAN, GAN_TO_GEN) name maps for the weight-sync protocol."""
    n = len(cfg.hidden)
    start = 2 + n + 1
    dis_to_gan = {"dis_batch_layer_1": f"gan_dis_batch_layer_{start}"}
    for k in range(n):
        dis_to_gan[f"dis_dense_layer_{2 + k}"] = f"gan_dis_dense_layer_{start + 1 + k}"
    dis_to_gan[f"dis_output_layer_{2 + n}"] = f"gan_dis_output_layer_{start + 1 + n}"
    gan_to_gen = {"gan_batch_1": "gen_batch_1"}
    for k in range(n + 1):
        gan_to_gen[f"gan_dense_layer_{2 + k}"] = f"gen_dense_layer_{2 + k}"
    return dis_to_gan, gan_to_gen


def synthetic_transactions(
    num_rows: int = 10000, num_features: int = 32, seed: int = 666
) -> np.ndarray:
    """Synthetic financial-transactions table, scaled to [0,1]: log-normal
    amounts, cyclic time-of-day pair, a merchant-category one-hot block, and
    correlated balance/velocity features — enough covariance structure that a
    GAN has something nontrivial to model. Deterministic per seed."""
    rng = np.random.default_rng(seed)
    cat = rng.integers(0, 8, size=num_rows)
    amount = rng.lognormal(mean=3.0 + 0.3 * cat, sigma=0.8, size=num_rows)
    hour = rng.normal(loc=9.0 + cat, scale=2.5, size=num_rows) % 24.0
    balance = amount * rng.uniform(5.0, 50.0, size=num_rows)
    velocity = rng.poisson(lam=1.0 + cat, size=num_rows).astype(np.float64)

    cols = [
        np.clip(np.log1p(amount) / 10.0, 0, 1),
        (np.sin(2 * np.pi * hour / 24.0) + 1.0) / 2.0,
        (np.cos(2 * np.pi * hour / 24.0) + 1.0) / 2.0,
        np.clip(np.log1p(balance) / 15.0, 0, 1),
        np.clip(velocity / 10.0, 0, 1),
    ]
    one_hot = np.eye(8)[cat]
    base = np.column_stack(cols + [one_hot])  # 13 structured columns
    if num_features < base.shape[1]:
        return base[:, :num_features].astype(np.float32)
    # remaining columns: noisy linear mixes of the structured ones
    extra = num_features - base.shape[1]
    mix = rng.normal(size=(base.shape[1], extra)) / np.sqrt(base.shape[1])
    noise = 0.05 * rng.normal(size=(num_rows, extra))
    rest = np.clip(base @ mix + 0.5 + noise, 0, 1)
    return np.column_stack([base, rest]).astype(np.float32)
