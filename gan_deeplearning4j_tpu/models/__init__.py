"""Model zoo: the reference DCGAN-MNIST family plus the BASELINE.md configs
(tabular MLP-GAN, CIFAR-10 DCGAN, CelebA-64 DCGAN, WGAN-GP critic)."""

from gan_deeplearning4j_tpu.models import dcgan_mnist

__all__ = ["dcgan_mnist"]
