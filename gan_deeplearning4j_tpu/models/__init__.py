"""Model zoo: the reference DCGAN-MNIST family plus the BASELINE.md configs
(tabular MLP-GAN, CIFAR-10/CelebA-64 image DCGANs, WGAN-GP)."""

from gan_deeplearning4j_tpu.models import dcgan_image, dcgan_mnist, mlp_gan, wgan_gp

__all__ = ["dcgan_image", "dcgan_mnist", "mlp_gan", "wgan_gp"]
