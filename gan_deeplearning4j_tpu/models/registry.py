"""GAN family registry — one uniform surface over the model zoo so the
experiment harness/bench can run any BASELINE.md config through the same
alternating loop (the reference's loop, dl4jGANComputerVision.java:408-621,
is model-agnostic: it only needs the three graphs + the sync maps).

A family provides: graph builders, the weight-sync maps, the synthetic data
source for offline runs, and (MNIST only) the transfer classifier."""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from gan_deeplearning4j_tpu.models import dcgan_image, dcgan_mnist, mlp_gan, wgan_gp


@dataclasses.dataclass(frozen=True)
class GanFamily:
    """Uniform model-family handle consumed by GanExperiment/bench."""

    name: str
    make_model_config: Callable  # ExperimentConfig-like -> family config
    build_discriminator: Callable
    build_generator: Callable
    # None for families with a bespoke loop (wgan_gp) — make_experiment then
    # supplies the experiment class instead of the stacked-graph protocol
    build_gan: Optional[Callable] = None
    sync_maps: Optional[Callable] = None  # family config -> (DIS_TO_GAN, GAN_TO_GEN)
    synthetic_data: Optional[Callable] = None  # (num, family config, seed) -> (N, F) f32
    # MNIST: the dis-feature transfer classifier (SURVEY I11); None elsewhere
    build_transfer_classifier: Optional[Callable] = None
    dis_to_cv: Optional[Dict[str, str]] = None
    # custom experiment factory: (ExperimentConfig, mesh) -> experiment with
    # the GanExperiment surface (train_iteration/run/save/load/exports)
    make_experiment: Optional[Callable] = None


def _mnist_config(cfg) -> dcgan_mnist.DcganConfig:
    return dcgan_mnist.DcganConfig(
        height=cfg.height, width=cfg.width, channels=cfg.channels,
        num_features=cfg.num_features, num_classes=cfg.num_classes,
        num_classes_dis=cfg.num_classes_dis, z_size=cfg.z_size,
        dis_learning_rate=cfg.dis_learning_rate,
        gen_learning_rate=cfg.gen_learning_rate,
        frozen_learning_rate=cfg.frozen_learning_rate,
        seed=cfg.seed, l2=cfg.l2, grad_clip=cfg.grad_clip,
    )


def _mnist_synthetic(num: int, model_cfg, seed: int) -> np.ndarray:
    from gan_deeplearning4j_tpu.data.mnist import synthetic_mnist

    (x, _), _ = synthetic_mnist(num_train=num, num_test=1, seed=seed)
    return x


def _mlp_config(cfg) -> mlp_gan.MlpGanConfig:
    return mlp_gan.MlpGanConfig(
        num_features=cfg.num_features, z_size=cfg.z_size,
        dis_learning_rate=cfg.dis_learning_rate,
        gen_learning_rate=cfg.gen_learning_rate,
        frozen_learning_rate=cfg.frozen_learning_rate,
        seed=cfg.seed, l2=cfg.l2, grad_clip=cfg.grad_clip,
    )


def _image_config(cfg) -> dcgan_image.ImageGanConfig:
    return dcgan_image.ImageGanConfig(
        height=cfg.height, width=cfg.width, channels=cfg.channels,
        z_size=cfg.z_size,
        dis_learning_rate=cfg.dis_learning_rate,
        gen_learning_rate=cfg.gen_learning_rate,
        frozen_learning_rate=cfg.frozen_learning_rate,
        seed=cfg.seed, l2=cfg.l2, grad_clip=cfg.grad_clip,
    )


_FAMILIES: Dict[str, GanFamily] = {
    "mnist": GanFamily(
        name="mnist",
        make_model_config=_mnist_config,
        build_discriminator=dcgan_mnist.build_discriminator,
        build_generator=dcgan_mnist.build_generator,
        build_gan=dcgan_mnist.build_gan,
        sync_maps=lambda cfg: (dcgan_mnist.DIS_TO_GAN, dcgan_mnist.GAN_TO_GEN),
        synthetic_data=_mnist_synthetic,
        build_transfer_classifier=dcgan_mnist.build_transfer_classifier,
        dis_to_cv=dcgan_mnist.DIS_TO_CV,
    ),
    "tabular": GanFamily(
        name="tabular",
        make_model_config=_mlp_config,
        build_discriminator=mlp_gan.build_discriminator,
        build_generator=mlp_gan.build_generator,
        build_gan=mlp_gan.build_gan,
        sync_maps=mlp_gan.sync_maps,
        synthetic_data=lambda num, cfg, seed: mlp_gan.synthetic_transactions(
            num, num_features=cfg.num_features, seed=seed
        ),
    ),
    "image": GanFamily(
        name="image",
        make_model_config=_image_config,
        build_discriminator=dcgan_image.build_discriminator,
        build_generator=dcgan_image.build_generator,
        build_gan=dcgan_image.build_gan,
        sync_maps=dcgan_image.sync_maps,
        synthetic_data=lambda num, cfg, seed: dcgan_image.synthetic_images(
            num, cfg, seed=seed
        ),
    ),
    "wgan_gp": GanFamily(
        name="wgan_gp",
        make_model_config=lambda cfg: wgan_gp.WganGpConfig(
            height=cfg.height, width=cfg.width, channels=cfg.channels,
            z_size=cfg.z_size, seed=cfg.seed,
            n_critic=cfg.n_critic, gp_lambda=cfg.gp_lambda,
        ),
        build_discriminator=wgan_gp.build_critic,
        build_generator=wgan_gp.build_generator,
        synthetic_data=lambda num, cfg, seed: dcgan_image.synthetic_images(
            num, cfg, seed=seed
        ),
        make_experiment=lambda cfg, mesh: _wgan_experiment(cfg, mesh),
    ),
}
# BASELINE.md config aliases
_ALIASES = {"cifar10": "image", "celeba64": "image"}


def _wgan_experiment(cfg, mesh):
    from gan_deeplearning4j_tpu.harness.wgan_experiment import WganGpExperiment

    return WganGpExperiment(cfg, mesh=mesh)


_BUILTINS = frozenset(_FAMILIES)


def register(family: GanFamily, *, overwrite: bool = False) -> GanFamily:
    """Add a user-defined family to the registry (the extension point the
    reference lacks — its topology is hardwired in one Java class). The
    experiment harness, bench, and CLI then accept ``family.name`` like any
    built-in."""
    if family.name in _ALIASES:
        # get() resolves aliases before families — a family registered under
        # an alias name would be silently unreachable
        raise ValueError(
            f"family name {family.name!r} collides with the "
            f"{_ALIASES[family.name]!r} alias"
        )
    if family.name in _BUILTINS:
        # irreversible either way: unregister refuses built-ins, so a
        # clobbered one could never be restored
        raise ValueError(f"cannot replace built-in family {family.name!r}")
    if family.name in _FAMILIES and not overwrite:
        raise ValueError(f"family {family.name!r} already registered")
    _FAMILIES[family.name] = family
    return family


def unregister(name: str) -> None:
    """Remove a user-registered family (tests use this to stay hermetic).
    Built-ins are not removable — losing e.g. 'mnist' would break the
    default bench/CLI path process-wide with a bare KeyError much later."""
    if name in _BUILTINS:
        raise ValueError(f"cannot unregister built-in family {name!r}")
    _FAMILIES.pop(name, None)


def names() -> Tuple[str, ...]:
    return tuple(_FAMILIES) + tuple(_ALIASES)


def get(name: str) -> GanFamily:
    key = _ALIASES.get(name, name)
    if key not in _FAMILIES:
        raise KeyError(f"unknown model family {name!r}; known: {sorted(names())}")
    return _FAMILIES[key]
