"""Deploy-plane CLI — ``python -m gan_deeplearning4j_tpu.deploy probe``.

The fleet-admission sidecar (docs/FLEET.md): measure one serving bundle's
quality probe in its OWN process and print the probe dict as one JSON
line. The fleet manager runs this against the candidate and the incumbent
bundle, then decides admission once per fleet via
:func:`~.canary.compare_probes` — serving workers never pay the probe's
compiles or device time, and a poisoned candidate is rejected before any
worker process ever loads it.

    python -m gan_deeplearning4j_tpu.deploy probe \\
        --bundle store/generations/gen-00000007 --data workload.npz

``--feature dis_features`` embeds both real rows and generated samples in
the discriminator-feature space of ``--feature-bundle``'s classifier (the
incumbent, so candidate and incumbent probes share one feature space);
the default is raw-row FID.
"""

from __future__ import annotations

import argparse
import json
import sys


def _probe(args) -> dict:
    import numpy as np

    from gan_deeplearning4j_tpu.deploy.canary import (
        classifier_from_bundle,
        feature_fn_from_checkpoint,
        load_quality_probe,
    )
    from gan_deeplearning4j_tpu.serving.engine import ServingEngine

    with np.load(args.data) as npz:
        features = npz["features"]
        labels = npz["labels"] if "labels" in npz.files else None
    feature_fn = None
    if args.feature == "dis_features":
        ref_bundle = args.feature_bundle or args.bundle
        resolved = classifier_from_bundle(ref_bundle)
        if resolved is None:
            raise ValueError(
                f"--feature dis_features needs a classifier with a feature "
                f"vertex in {ref_bundle}/serving.json")
        feature_fn = feature_fn_from_checkpoint(*resolved)
    # one replica, no gauge claim, lazy compiles: a sidecar probe must
    # never look like a serving process to the telemetry plane
    engine = ServingEngine.from_bundle(args.bundle, replicas=1,
                                       export_gauge=False)
    quality_probe = load_quality_probe()
    classify_fn = None
    if "classify" in engine.kinds and labels is not None:
        classify_fn = lambda rows: engine.run("classify", rows)  # noqa: E731
    probe = quality_probe(
        lambda z: engine.run("sample", z),
        features,
        z_size=engine.input_width("sample"),
        num_samples=min(args.samples, features.shape[0]),
        seed=args.seed,
        classify_fn=classify_fn,
        labels=labels,
        feature_fn=feature_fn,
    )
    probe["generation"] = engine.generation
    probe["feature"] = args.feature
    return probe


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gan_deeplearning4j_tpu.deploy",
        description="deploy-plane sidecar tools",
    )
    sub = p.add_subparsers(dest="command", required=True)
    pr = sub.add_parser(
        "probe", help="measure one bundle's quality probe; print JSON")
    pr.add_argument("--bundle", required=True,
                    help="serving bundle directory (contains serving.json)")
    pr.add_argument("--data", required=True,
                    help="npz with 'features' (and optionally 'labels')")
    pr.add_argument("--samples", type=int, default=256)
    pr.add_argument("--seed", type=int, default=666)
    pr.add_argument("--feature", choices=("raw", "dis_features"),
                    default="raw",
                    help="FID feature space: raw rows, or the "
                         "discriminator features of --feature-bundle's "
                         "classifier")
    pr.add_argument("--feature-bundle", default=None,
                    help="bundle whose classifier defines the dis-feature "
                         "space (default: --bundle; the fleet manager "
                         "passes the incumbent)")
    args = p.parse_args(argv)
    try:
        probe = _probe(args)
    except Exception as exc:  # one JSON error line, nonzero exit
        print(json.dumps({"error": f"{type(exc).__name__}: {exc}"}))
        return 1
    print(json.dumps(probe))
    return 0


if __name__ == "__main__":
    sys.exit(main())
