"""ReloadController — the zero-downtime generation-reload control plane.

One background thread runs the reload cycle against a live
``serving.InferenceService``:

1. **watch** — :class:`~.watcher.StoreWatcher` finds a digest-valid
   serving bundle newer than the served generation (corrupt generations
   are quarantined and skipped; poll errors back off exponentially up to
   ``backoff_max``).
2. **warm** — the candidate :class:`~serving.engine.ServingEngine` is
   constructed and AOT-warmed OFF-THREAD (this thread), against the live
   engine's bucket ladder and replica count, with
   ``export_gauge=False`` so a warming candidate never claims the
   process-wide ``serving_generation`` gauge. The live engine keeps
   serving from its compiled executables throughout — candidate compiles
   serialize on the candidate's own locks, never the live engine's.
3. **canary** — the :class:`~.canary.CanaryGate` (when configured) probes
   candidate and incumbent with the same fixed seeded batch; a failing
   candidate is quarantined through the store's machinery and NEVER
   served.
4. **swap** — ``MicroBatcher.swap_engine`` atomically routes future
   flushes to the candidate under the batcher lock. In-flight flights
   finalize on the old engine (they carry it on the flight record), new
   flushes dispatch on the new one, and nothing is shed or lost in
   between. The old engine is retired once its last flight drains
   (``flights_on(old) == 0``), then dropped.

Candidate state (``idle``/``warming``/``canary``/``swapping``/
``rejected``), swap and rejection counts, and the active generation are
exported through the telemetry registry and surfaced in ``/healthz``
(docs/DEPLOY.md); ``POST /admin/reload`` forces an immediate poll via
:meth:`poll_now`.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from gan_deeplearning4j_tpu.deploy.watcher import BundleCandidate, StoreWatcher
from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import TRACER

logger = logging.getLogger(__name__)

#: candidate states, in gauge order (deploy_candidate_state exports the
#: index: idle=0, warming=1, canary=2, swapping=3, rejected=4)
STATES = ("idle", "warming", "canary", "swapping", "rejected")
_STATE_CODE = {name: i for i, name in enumerate(STATES)}


class ReloadBusy(RuntimeError):
    """A forced poll arrived while a reload cycle is already running —
    the /admin/reload 409, mirroring /debug/trace's CaptureBusy."""


def _default_build(candidate: BundleCandidate, live):
    """Construct the candidate engine against the LIVE engine's shape:
    same bucket ladder, same replica count — so its AOT warmup compiles
    exactly the executables the batcher will route to after the swap."""
    from gan_deeplearning4j_tpu.serving.engine import ServingEngine

    return ServingEngine.from_bundle(
        candidate.path,
        buckets=live.buckets,
        replicas=live.replica_count,
        export_gauge=False,
    )


def _ladder_priority(manifest_buckets, learned, incumbent):
    """The candidate-build bucket resolution order (docs/SERVING.md):
    a ladder the bundle's own manifest carries (per-variant, persisted
    at publish time) > one solved live from the incumbent's recorded
    traffic > the incumbent's ladder itself."""
    return manifest_buckets or learned or incumbent


class ReloadController:
    """Drives watch → warm → canary → swap against one service.

    ``build`` is injectable for tests: ``(BundleCandidate, live_engine) ->
    engine``; the default loads a ``ServingEngine`` from the candidate
    bundle. ``canary=None`` disables the quality gate (digest verification
    still applies — the watcher never offers a corrupt bundle)."""

    def __init__(self, service, watcher: StoreWatcher, *,
                 canary=None, poll_interval: float = 2.0,
                 backoff_max: float = 30.0, drain_timeout: float = 30.0,
                 build: Optional[Callable] = None,
                 registry=None, adopt_weight: float = 0.0,
                 adopt_cost: float = 1.0,
                 adopt_name: str = "gen-{generation}"):
        """``registry`` switches the controller into MUX mode
        (docs/MULTIPLEX.md): an admitted candidate is not swapped into a
        singleton engine but ADOPTED into the
        :class:`~serving.mux.MuxRegistry` as a new named variant —
        ``adopt_name`` formatted with the store generation, at
        ``adopt_weight`` (default 0: no traffic until a ramp admits it)
        and ``adopt_cost`` (the brownout shed order). The candidate is
        built against the registry's bucket ladder/replicas with the
        shared staging pool, the watcher polls against the registry's
        newest variant generation, and the compatibility + canary gates
        compare against the registry's primary (highest-weighted
        resident) engine. ``service`` may be None in this mode."""
        if poll_interval <= 0:
            raise ValueError("poll_interval must be > 0")
        if service is None and registry is None:
            raise ValueError("need a service (singleton mode) or a "
                             "registry (mux mode)")
        self.service = service
        self.watcher = watcher
        self.canary = canary
        self.poll_interval = poll_interval
        self.backoff_max = backoff_max
        self.drain_timeout = drain_timeout
        self.registry = registry
        self.adopt_weight = adopt_weight
        self.adopt_cost = adopt_cost
        self.adopt_name = adopt_name
        if build is None:
            build = (self._registry_build if registry is not None
                     else self._singleton_build)
        self._build = build
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # forced-poll sequencing: poll_now(wait=True) must return the
        # outcome of a cycle that STARTED after the request — _force_seq
        # is the request counter, _done_seq the newest request a finished
        # cycle had seen at its start
        self._force_seq = 0
        self._done_seq = 0
        self._busy = False
        self._state = "idle"
        self._candidate_generation: Optional[int] = None
        # directory-mode watchers are primed with the CURRENT manifest
        # token, so the bundle the server already serves is never
        # re-offered as a "new" candidate on the first poll
        self._current_token: Optional[str] = (
            None if watcher.path is None
            else StoreWatcher.dir_token(watcher.path))
        self._swaps = 0
        self._adopted = 0
        self._rejected = 0
        self._last_error: Optional[str] = None
        self.events: list = []  # swap/adopt/reject records, newest last
        registry = get_registry()
        self._c_adoptions = registry.counter(
            "deploy_adoptions_total",
            "candidate generations adopted into the mux registry "
            "(registry-mode reloads; docs/MULTIPLEX.md)")
        self._c_swaps = registry.counter(
            "deploy_swaps_total",
            "zero-downtime engine swaps completed by the reload plane")
        self._c_rejects = registry.counter(
            "deploy_rejects_total",
            "candidate generations rejected (canary failure, construction "
            "failure, kind mismatch)")
        self._h_swap = registry.histogram(
            "deploy_swap_seconds",
            "wall seconds per swap (atomic switch + old-engine drain)")
        self._g_state = registry.gauge(
            "deploy_candidate_state",
            "reload candidate state: 0=idle 1=warming 2=canary 3=swapping "
            "4=rejected")
        self._g_state.set(0)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> threading.Thread:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self._thread
            self._stop.clear()
            t = threading.Thread(target=self._loop, name="deploy-reloader",
                                 daemon=True)
            self._thread = t
        t.start()
        return t

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    # -- observability --------------------------------------------------
    def status(self) -> dict:
        """The /healthz "reload" block."""
        with self._lock:
            return {
                "state": self._state,
                "mode": "registry" if self.registry is not None else "swap",
                "candidate_generation": self._candidate_generation,
                "swaps": self._swaps,
                "adopted": self._adopted,
                "rejected": self._rejected,
                "last_error": self._last_error,
            }

    def _transition(self, state: str, candidate_generation) -> None:
        with self._lock:
            self._state = state
            self._candidate_generation = candidate_generation
        self._g_state.set(_STATE_CODE[state])

    def _learned_buckets(self, live):
        """Solve a ladder from the INCUMBENT's recorded request sizes
        (serving/ladder.py) under the incumbent's compile budget and top
        bucket — the carry-forward that lets a new generation boot with
        buckets shaped by the traffic it is about to inherit. None when
        nothing was recorded yet (or on any solver hiccup: a reload must
        never fail over ladder learning). The solve is in-memory on
        purpose — a published generation's bytes are digest-immutable
        (resilience store) and the directory-mode watcher tokens hash
        ``serving.json``, so the reload plane never writes the block
        into a candidate bundle; ``write_ladder_block`` is for
        publishers, BEFORE the bundle is digested."""
        if live is None:
            return None
        try:
            if self.registry is not None:
                name = self.registry.primary_name()
                if name is None:
                    return None
                hist = self.registry.variant(name).histogram
            else:
                hist = getattr(self.service.batcher, "size_histogram", None)
            if hist is None:
                return None
            counts = hist.merged()
            if not counts:
                return None
            from gan_deeplearning4j_tpu.serving.ladder import solve_ladder

            return solve_ladder(counts, len(live.buckets),
                                top=live.buckets[-1])
        except Exception:
            logger.exception("learned-ladder solve failed — candidate "
                             "keeps the incumbent ladder")
            return None

    def _singleton_build(self, candidate: BundleCandidate, live):
        """Singleton-mode candidate construction: the bundle's own
        manifest ladder > a ladder solved from the incumbent batcher's
        histogram > the live ladder (same top + budget either way, so
        the batcher's ``max_batch`` and chunking contract carry across
        the swap); replica count always the live engine's."""
        from gan_deeplearning4j_tpu.serving.engine import ServingEngine
        from gan_deeplearning4j_tpu.serving.ladder import manifest_ladder

        buckets = _ladder_priority(manifest_ladder(candidate.path),
                                   self._learned_buckets(live),
                                   live.buckets)
        return ServingEngine.from_bundle(
            candidate.path,
            buckets=buckets,
            replicas=live.replica_count,
            export_gauge=False,
        )

    def _registry_build(self, candidate: BundleCandidate, live):
        """Mux-mode candidate construction: the registry's ONE build
        recipe (ladder + replicas + shared staging pool), so adopted
        candidates and budget re-warms can never diverge in config. The
        incumbent-traffic solve rides along as the fallback for bundles
        with no manifest ladder of their own."""
        return self.registry.build_engine(
            candidate.path, fallback_buckets=self._learned_buckets(live))

    # -- forced polls (POST /admin/reload) ------------------------------
    def poll_now(self, wait: bool = False, timeout: float = 60.0) -> dict:
        """Skip the remainder of the watcher interval and poll NOW.
        ``wait=True`` blocks until a cycle that STARTED after this request
        finishes (the /admin/reload ``block=1`` path — a cycle already
        winding down when the request lands does not count as its
        outcome); raises :class:`ReloadBusy` when a cycle is already in
        progress."""
        with self._lock:
            if self._busy:
                raise ReloadBusy("a reload cycle is already in progress")
            running = self._thread is not None and self._thread.is_alive()
            if running:
                self._force_seq += 1
                target = self._force_seq
        if not running:
            # no loop thread (tests, or a stopped controller): run one
            # cycle synchronously — same code path, caller's thread
            self._cycle()
            return self.status()
        self._wake.set()
        if wait:
            with self._cond:
                self._cond.wait_for(lambda: self._done_seq >= target,
                                    timeout=timeout)
        return self.status()

    # -- the loop --------------------------------------------------------
    def _loop(self) -> None:
        delay = self.poll_interval
        while not self._stop.is_set():
            with self._lock:
                seen = self._force_seq  # requests this cycle will cover
            try:
                self._cycle()
                delay = self.poll_interval
            except Exception as exc:  # store unreachable etc. — back off
                with self._lock:
                    self._last_error = f"{type(exc).__name__}: {exc}"
                self._transition("idle", None)
                delay = min(self.backoff_max,
                            max(self.poll_interval, delay * 2))
                logger.warning("reload poll failed (%s) — backing off %.1fs",
                               exc, delay)
            with self._cond:
                self._done_seq = seen
                self._cond.notify_all()
            if self._stop.is_set():
                return
            self._wake.wait(delay)
            self._wake.clear()

    def _cycle(self) -> bool:
        """One watch→warm→canary→swap pass. True when a candidate was
        handled (swapped or rejected), False when nothing newer exists."""
        with self._lock:
            self._busy = True
        try:
            if self.registry is not None:
                # mux mode: "newer" means newer than ANY adopted variant,
                # and the compatibility/canary reference is the registry's
                # primary (None while the registry bootstraps — the first
                # adopted generation then lands ungated-by-comparison)
                live = self.registry.reference_engine()
                current_generation = self.registry.max_generation()
            else:
                live = self.service.engine
                current_generation = live.generation
            candidate = self.watcher.poll_once(
                current_generation=current_generation,
                current_token=self._current_token,
            )
            if candidate is None:
                self._transition("idle", None)
                return False
            return self._process(candidate, live)
        finally:
            with self._lock:
                self._busy = False

    def _process(self, candidate: BundleCandidate, live) -> bool:
        gen = candidate.generation
        self._transition("warming", gen)
        try:
            with TRACER.span("deploy.warm", generation=gen):
                engine = self._build(candidate, live)
                engine.warmup()  # sync: full ladder, every replica
        except Exception as exc:
            # unbuildable = unservable: discard (and quarantine, when the
            # generation still exists — a GC'd-underneath read is just
            # skipped, not flagged)
            self._reject(candidate,
                         f"engine construction failed: "
                         f"{type(exc).__name__}: {exc}", quarantine=True)
            return True
        if live is None:
            # mux bootstrap: nothing to compare kinds/widths/quality
            # against — the first variant defines the reference
            return self._adopt(candidate, engine)
        missing = set(live.kinds) - set(engine.kinds)
        if missing:
            # a bundle that dropped request kinds would 404 live traffic
            # mid-flight — config mismatch, not corruption: skip it without
            # quarantining the bytes
            self._reject(candidate,
                         f"candidate serves no {sorted(missing)} but the "
                         f"live engine does", quarantine=False)
            return True
        mismatched = [
            k for k in live.kinds
            if engine.input_width(k) != live.input_width(k)
        ]
        if mismatched:
            # same kinds, different request shapes (a changed z_size or
            # feature width): rows validated against the live engine would
            # error the flush they ride after the swap — config mismatch
            self._reject(candidate,
                         f"candidate input width differs for {mismatched} "
                         f"(live: {[live.input_width(k) for k in mismatched]}"
                         f", candidate: "
                         f"{[engine.input_width(k) for k in mismatched]})",
                         quarantine=False)
            return True
        if self.canary is not None:
            self._transition("canary", gen)
            with TRACER.span("deploy.canary", generation=gen):
                decision = self.canary.evaluate(engine, live)
            if not decision.passed:
                TRACER.instant("deploy.canary_reject", {
                    "generation": gen, "reason": decision.reason})
                self._reject(candidate, f"canary: {decision.reason}",
                             quarantine=True,
                             extra={"candidate_probe": decision.candidate,
                                    "incumbent_probe": decision.incumbent})
                return True
        if self.registry is not None:
            return self._adopt(candidate, engine)
        self._transition("swapping", gen)
        t0 = time.perf_counter()
        old = self.service.batcher.swap_engine(engine)
        engine.export_generation()  # the gauge follows the SERVED engine
        drained = self._drain(old)
        t1 = time.perf_counter()
        TRACER.complete("deploy.swap", t0, t1, {
            "from_generation": old.generation,
            "to_generation": engine.generation,
            "drained": drained,
        })
        self._c_swaps.inc()
        self._h_swap.observe(t1 - t0)
        with self._lock:
            self._swaps += 1
            self._current_token = candidate.token
            self._last_error = None
            self.events.append({
                "event": "swap", "from": old.generation,
                "to": engine.generation, "seconds": t1 - t0,
                "drained": drained,
            })
        self._transition("idle", None)
        logger.info("swapped serving engine: generation %s -> %s (%.3fs)",
                    old.generation, engine.generation, t1 - t0)
        return True

    def _adopt(self, candidate: BundleCandidate, engine) -> bool:
        """Mux-mode admission: the warmed (and canaried) candidate joins
        the registry as a new variant instead of replacing a singleton —
        at ``adopt_weight`` (default 0: resident and warm, serving
        nothing until a ramp or an operator gives it weight). Nothing
        drains: every incumbent variant keeps serving untouched."""
        gen = candidate.generation
        name = self.adopt_name.format(generation=gen)
        self._transition("swapping", gen)
        try:
            with TRACER.span("deploy.adopt", generation=gen):
                self.registry.adopt(
                    name, engine, bundle_path=candidate.path,
                    cost=self.adopt_cost, weight=self.adopt_weight,
                    generation=gen)
        except ValueError as exc:
            # a name collision is a config problem, not corruption
            self._reject(candidate, f"adopt failed: {exc}",
                         quarantine=False)
            return True
        self._c_adoptions.inc()
        with self._lock:
            self._adopted += 1
            self._current_token = candidate.token
            self._last_error = None
            self.events.append({
                "event": "adopt", "generation": gen, "variant": name,
                "weight": self.adopt_weight,
            })
        self._transition("idle", None)
        logger.info("adopted serving generation %s as mux variant %r "
                    "(weight %.3f)", gen, name, self.adopt_weight)
        return True

    def _drain(self, old) -> bool:
        """Wait for the old engine's last flight: the batcher stops
        routing to it at the swap, so its pipeline count only falls. True
        when fully drained within ``drain_timeout`` (the engine is then
        retired — dropped, its buffers and executables freed with it)."""
        deadline = time.monotonic() + self.drain_timeout
        while (self.service.batcher.flights_on(old) > 0
               or old.in_flight > 0):
            if time.monotonic() >= deadline:
                logger.warning(
                    "old engine still has flights after %.1fs drain window",
                    self.drain_timeout)
                return False
            time.sleep(0.005)
        return True

    def _reject(self, candidate: BundleCandidate, reason: str,
                quarantine: bool, extra: Optional[dict] = None) -> None:
        self.watcher.discard(candidate, reason, quarantine=quarantine)
        self._c_rejects.inc()
        with self._lock:
            self._rejected += 1
            self._last_error = reason
            self.events.append({
                "event": "reject", "generation": candidate.generation,
                "reason": reason, "quarantined": quarantine,
                **(extra or {}),
            })
        self._transition("rejected", candidate.generation)
        logger.warning("candidate generation %s rejected: %s",
                       candidate.generation, reason)
