"""StoreWatcher — discovers newer digest-valid serving bundles to reload.

The reload plane's read side. Two sources, one contract:

- **store mode** — poll a ``resilience.CheckpointStore`` for published
  generations newer than the one currently served
  (``generations_newer_than``), newest first. A generation that fails
  digest verification is moved to quarantine through the store's existing
  machinery and the walk falls back — the *corrupt-generation skip*: a
  half-written or bit-flipped bundle is never offered to the reloader.
  Generations without a ``serving.json`` (training checkpoints sharing a
  store) are remembered and skipped silently.
- **directory mode** — poll a bare ``serving.json`` bundle directory (the
  unversioned ``publish_for_serving(directory=)`` flow). Bundles there
  carry no generation number, so "newer" is "the manifest bytes changed":
  the candidate token is a content hash of ``serving.json`` (which the
  publisher lands atomically, so a torn read is impossible).

The watcher also owns the *skip memory*: a candidate the reloader rejected
(canary failure, construction failure, kind mismatch) is recorded via
:meth:`discard` and never offered again — in store mode optionally through
the store's quarantine, which is what keeps a canary-failed generation out
of every FUTURE server's view too, not just this process's.

Polling cadence and backoff live in the :class:`~.reloader.ReloadController`
loop; this class is one synchronous, side-effect-bounded ``poll_once``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Optional, Set

from gan_deeplearning4j_tpu.resilience.store import (
    MANIFEST_NAME,
    gen_dirname,
)

#: the bundle manifest every servable candidate must contain
SERVING_MANIFEST = "serving.json"


@dataclasses.dataclass(frozen=True)
class BundleCandidate:
    """One reloadable bundle the watcher found. ``generation`` is the
    store generation number (None in directory mode); ``token`` uniquely
    identifies the candidate across polls (the skip-memory key)."""

    path: str
    generation: Optional[int]
    token: str
    manifest: dict


class StoreWatcher:
    """``poll_once`` returns the newest candidate worth reloading, or
    None. Construct with exactly one of ``store`` (a
    ``resilience.CheckpointStore``) or ``path`` (a bundle directory)."""

    def __init__(self, store=None, path: Optional[str] = None):
        if (store is None) == (path is None):
            raise ValueError("pass exactly one of store= or path=")
        self.store = store
        self.path = path
        self._rejected: Set[str] = set()
        self._not_serving: Set[int] = set()  # training generations, by number

    # -- discovery ------------------------------------------------------
    def poll_once(self, current_generation: Optional[int] = None,
                  current_token: Optional[str] = None
                  ) -> Optional[BundleCandidate]:
        """The newest digest-valid serving candidate newer than what is
        currently served (``current_generation`` in store mode,
        ``current_token`` in directory mode), skipping rejected and
        non-serving entries and quarantining corrupt ones."""
        if self.store is not None:
            return self._poll_store(current_generation)
        return self._poll_dir(current_token)

    def _poll_store(self, current: Optional[int]
                    ) -> Optional[BundleCandidate]:
        for number in reversed(self.store.generations_newer_than(current)):
            token = gen_dirname(number)
            if token in self._rejected or number in self._not_serving:
                continue
            path = os.path.join(self.store.generations_dir,
                                gen_dirname(number))
            # the cheap check FIRST: a training checkpoint sharing the
            # store (no serving.json) is skipped without hashing a single
            # byte — and is never the serving plane's to quarantine
            if not os.path.exists(os.path.join(path, SERVING_MANIFEST)):
                if os.path.isdir(path):
                    self._not_serving.add(number)
                # else: GC'd between the scan and here — just move on
                continue
            reason = self.store.verify(number)
            if reason is not None:
                # corrupt-generation skip: quarantine through the store's
                # machinery (dir moved aside + ledger-flagged) and fall
                # back to the next-newest candidate — unless the writer's
                # retention GC deleted it underneath this walk, which is
                # not corruption and must not leave a bogus ledger flag
                if number in self.store.published():
                    self.store.quarantine(number, reason)
                continue
            with open(os.path.join(path, MANIFEST_NAME)) as fh:
                manifest = json.load(fh)
            return BundleCandidate(path=path, generation=number,
                                   token=token, manifest=manifest)
        return None

    def _poll_dir(self, current_token: Optional[str]
                  ) -> Optional[BundleCandidate]:
        try:
            with open(os.path.join(self.path, SERVING_MANIFEST), "rb") as fh:
                raw = fh.read()
        except OSError:
            return None  # no bundle (yet) — not an error, just nothing new
        token = "sha256:" + hashlib.sha256(raw).hexdigest()
        if token == current_token or token in self._rejected:
            return None
        try:
            manifest = json.loads(raw)
        except json.JSONDecodeError:
            return None  # publisher lands serving.json atomically; a torn
            # manifest means something else wrote here — don't offer it
        return BundleCandidate(path=self.path,
                               generation=manifest.get("generation"),
                               token=token, manifest=manifest)

    @staticmethod
    def dir_token(path: str) -> Optional[str]:
        """Content token of a bundle directory's current ``serving.json``
        (None when absent) — primes directory-mode tracking so the bundle
        the server just loaded is not immediately 're-loaded'."""
        try:
            with open(os.path.join(path, SERVING_MANIFEST), "rb") as fh:
                return "sha256:" + hashlib.sha256(fh.read()).hexdigest()
        except OSError:
            return None

    # -- skip memory ----------------------------------------------------
    def discard(self, candidate: BundleCandidate, reason: str,
                quarantine: bool = False) -> None:
        """Never offer ``candidate`` again. ``quarantine=True`` (store
        mode) additionally moves the generation aside through the store's
        quarantine machinery — a canary-failed generation is then invisible
        to every future reader, not just this watcher."""
        self._rejected.add(candidate.token)
        if (quarantine and self.store is not None
                and candidate.generation is not None
                and candidate.generation in self.store.published()):
            self.store.quarantine(candidate.generation, reason)
