"""deploy/ — zero-downtime generation reload: the train→serve loop closed.

The two halves existed and pointed at each other — ``resilience/store.py``
publishes digest-verified, versioned serving bundles and
``serving/engine.py`` restores from them — but a running server never
noticed a newer generation. This package is the control plane between
them, the "model updates while millions of users are connected" story
(ROADMAP; the continuous-training→live-serving shape of the
TensorFlow-system paper in PAPERS.md):

- :mod:`.watcher` — polls the checkpoint store ledger (or a bare
  ``serving.json`` bundle directory) for a newer digest-valid serving
  generation, quarantining corrupt generations through the store's
  existing machinery and skipping them;
- :mod:`.canary` — a quality gate between "the bytes verify" and "this
  model serves": the same FID/classifier-accuracy probe
  ``scripts/quality_run.py`` uses (imported, not shelled out), run on a
  fixed seeded batch, thresholds RELATIVE to the incumbent; a failing
  generation is quarantined and never served;
- :mod:`.reloader` — constructs the candidate engine off-thread, AOT-warms
  it against the live engine's bucket ladder and replica set, then
  atomically swaps engines under the batcher: in-flight flights finalize
  on the old engine, new flushes dispatch on the new one, zero requests
  shed or lost during the swap; the old engine is retired after its last
  flight. Candidate state, swap count, and the active generation export
  through the telemetry registry, ``/healthz``, and ``POST /admin/reload``.

The training side feeds this plane via the supervisor's serve-publish
cadence (``python -m gan_deeplearning4j_tpu.resilience --serve-store``),
and ``scripts/reload_drill.py`` proves the whole loop against real
subprocesses. Architecture notes: docs/DEPLOY.md.
"""

from gan_deeplearning4j_tpu.deploy.canary import (
    CanaryDecision,
    CanaryGate,
    CanaryThresholds,
    compare_probes,
    classifier_from_bundle,
    feature_fn_from_checkpoint,
    load_quality_probe,
)
from gan_deeplearning4j_tpu.deploy.reloader import (
    ReloadBusy,
    ReloadController,
    STATES,
)
from gan_deeplearning4j_tpu.deploy.watcher import BundleCandidate, StoreWatcher

__all__ = [
    "BundleCandidate",
    "CanaryDecision",
    "CanaryGate",
    "CanaryThresholds",
    "ReloadBusy",
    "ReloadController",
    "STATES",
    "StoreWatcher",
    "compare_probes",
    "classifier_from_bundle",
    "feature_fn_from_checkpoint",
    "load_quality_probe",
]
