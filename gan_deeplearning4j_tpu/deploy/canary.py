"""Canary gate — a candidate engine must prove quality before it serves.

Digest verification (the store) proves a bundle holds exactly the bytes
its writer produced; it says nothing about whether those bytes are a good
model. A training run can publish a collapsed generator or a corrupted-by-
construction state with perfectly valid digests. The canary gate closes
that hole: before the reloader swaps a candidate in, it runs the SAME
quality probe ``scripts/quality_run.py`` uses (imported, not shelled out —
one definition of "quality" across the quality run and the reload plane)
on a fixed seeded batch against both the candidate and the incumbent, and
admits the candidate only when its numbers hold up *relative to the
incumbent*:

- **FID probe** — Fréchet distance between the candidate's seeded sample
  batch and the real rows (raw-row features by default; pass
  ``feature_fn`` — e.g. ``eval.fid.frozen_feature_fn`` — for image-family
  bundles). Gate: ``candidate_fid <= incumbent_fid × fid_ratio_max +
  fid_slack`` (the additive slack keeps near-zero incumbents from making
  the ratio test vacuous-strict).
- **classifier accuracy** — the frozen-feature transfer classifier scored
  on labeled real rows. Gate: ``candidate_acc >= incumbent_acc -
  accuracy_drop_max``. Skipped when the bundle serves no classifier or no
  labels were provided.

Thresholds are RELATIVE by design: an absolute FID bar would need
re-tuning per dataset/model family, but "not dramatically worse than what
is serving right now" transfers. The incumbent's probe is cached per
(engine, generation) so steady-state reloads pay one candidate probe each.

A failing candidate is never served; the reloader quarantines its
generation through the store's existing machinery (docs/DEPLOY.md).
"""

from __future__ import annotations

import dataclasses
import importlib.util
import json
import os
from typing import Callable, Optional, Tuple

import numpy as np

_probe_fn = None  # the lazily imported scripts/quality_run.quality_probe


def load_quality_probe() -> Callable:
    """Import ``quality_probe`` from ``scripts/quality_run.py`` (the repo
    scripts directory is not a package, so this goes through importlib).
    One definition of the probe — the quality run CLI and this gate can
    never disagree about what the numbers mean."""
    global _probe_fn
    if _probe_fn is not None:
        return _probe_fn
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(root, "scripts", "quality_run.py")
    if not os.path.exists(path):
        raise RuntimeError(
            f"cannot locate scripts/quality_run.py (looked at {path}) — "
            f"the canary gate needs its quality_probe")
    spec = importlib.util.spec_from_file_location("_gdt_quality_run", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    _probe_fn = module.quality_probe
    return _probe_fn


@dataclasses.dataclass(frozen=True)
class CanaryThresholds:
    """Relative quality bars (see module docstring for semantics)."""

    fid_ratio_max: float = 1.5
    fid_slack: float = 10.0
    accuracy_drop_max: float = 0.05


@dataclasses.dataclass
class CanaryDecision:
    """Outcome of one gate evaluation, with both probes for the record."""

    passed: bool
    reason: str
    candidate: dict
    incumbent: dict


def compare_probes(candidate: dict, incumbent: dict,
                   thresholds: Optional[CanaryThresholds] = None
                   ) -> CanaryDecision:
    """The admission decision on two ALREADY-MEASURED probe dicts
    (``{"fid": float, "accuracy": float|None}``) — the seam the fleet
    manager's sidecar canary shares with the in-process gate: probes may
    run anywhere (another process, another host), but what "passes"
    means is defined exactly once (docs/FLEET.md)."""
    t = thresholds or CanaryThresholds()
    failures = []
    fid_limit = incumbent["fid"] * t.fid_ratio_max + t.fid_slack
    # written as not-<= so a NaN probe (degenerate samples) fails the
    # gate instead of slipping past a > comparison
    if not (candidate["fid"] <= fid_limit):
        failures.append(
            f"fid {candidate['fid']:.4g} exceeds limit {fid_limit:.4g} "
            f"(incumbent {incumbent['fid']:.4g} × {t.fid_ratio_max} + "
            f"{t.fid_slack})")
    if (candidate.get("accuracy") is not None
            and incumbent.get("accuracy") is not None):
        floor = incumbent["accuracy"] - t.accuracy_drop_max
        if not (candidate["accuracy"] >= floor):
            failures.append(
                f"accuracy {candidate['accuracy']:.4f} below floor "
                f"{floor:.4f} (incumbent {incumbent['accuracy']:.4f} - "
                f"{t.accuracy_drop_max})")
    return CanaryDecision(
        passed=not failures,
        reason="; ".join(failures) if failures else "ok",
        candidate=candidate,
        incumbent=incumbent,
    )


def feature_fn_from_checkpoint(classifier_path: str, vertex: str,
                               batch_size: int = 500):
    """Discriminator-feature extractor for the canary's FID: rows →
    activations at ``vertex`` of the checkpointed classifier (the
    dis-feature space the paper's transfer claim is about). The weights
    are pinned at load time, so candidate and incumbent are embedded in
    the SAME space regardless of how many generations later the gate
    runs — what ``--canary-feature dis_features`` maps to."""
    from gan_deeplearning4j_tpu.eval.fid import graph_feature_fn
    from gan_deeplearning4j_tpu.utils.serializer import read_model

    graph, params, _, _ = read_model(classifier_path, load_updater=False)
    if vertex not in {v.name for v in graph.vertices}:
        raise ValueError(
            f"feature vertex {vertex!r} is not a vertex of the classifier "
            f"graph")
    return graph_feature_fn(graph, params, vertex, batch_size=batch_size)


def classifier_from_bundle(directory: str) -> Optional[Tuple[str, str]]:
    """(classifier checkpoint path, feature vertex) from a serving
    bundle's ``serving.json``, or None when the bundle serves no
    dis-feature space — the one manifest resolution behind both the
    serving CLI's ``--canary-feature dis_features`` and the sidecar
    probe's ``--feature-bundle``."""
    with open(os.path.join(directory, "serving.json")) as fh:
        manifest = json.load(fh)
    name = manifest.get("classifier")
    vertex = manifest.get("feature_vertex")
    if name and vertex:
        return os.path.join(directory, name), vertex
    return None


class CanaryGate:
    """Probes engines with a fixed seeded batch and compares candidate
    against incumbent under :class:`CanaryThresholds`.

    ``features``/``labels`` are the real evaluation rows (labels optional
    — accuracy is then skipped). ``probe`` is injectable for tests: any
    ``engine -> {"fid": float, "accuracy": float|None}`` callable; the
    default wraps ``scripts/quality_run.quality_probe``."""

    def __init__(self, features, labels=None, *, num_samples: int = 256,
                 seed: int = 666, feature_fn=None,
                 thresholds: Optional[CanaryThresholds] = None,
                 probe: Optional[Callable] = None,
                 dataset: Optional[str] = None):
        self.features = np.asarray(features, dtype=np.float32)
        if self.features.ndim != 2 or self.features.shape[0] < 2:
            raise ValueError(
                f"canary needs (n >= 2, d) real rows, got "
                f"{self.features.shape}")
        self.labels = None if labels is None else np.asarray(labels)
        #: the zoo dataset identity of ``features`` (docs/ZOO.md). When set,
        #: a candidate bundle whose manifest declares a DIFFERENT dataset is
        #: rejected WITHOUT probing — a Fashion-MNIST generator FID-scored
        #: against MNIST reals is a meaningless number that could pass or
        #: fail arbitrarily, so the gate fails closed instead. None keeps
        #: the pre-zoo behavior (probe whatever arrives).
        self.dataset = dataset
        self.num_samples = int(num_samples)
        if self.num_samples < 2:
            raise ValueError("num_samples must be >= 2 (covariance fit)")
        self.seed = seed
        self.feature_fn = feature_fn
        self.thresholds = thresholds or CanaryThresholds()
        self._probe = probe
        # incumbent probe cache: (engine ref, generation) -> probe dict —
        # the strong ref pins the engine so an id() can never be recycled
        self._incumbent_cache = None

    # -- probing --------------------------------------------------------
    def probe(self, engine) -> dict:
        """One deterministic quality probe of ``engine`` (seeded z batch
        through ``run("sample")``, labeled rows through
        ``run("classify")`` when available)."""
        if self._probe is not None:
            return self._probe(engine)
        quality_probe = load_quality_probe()
        classify_fn = None
        if "classify" in engine.kinds and self.labels is not None:
            classify_fn = lambda rows: engine.run("classify", rows)  # noqa: E731
        sample_fn = lambda z: engine.run("sample", z)  # noqa: E731
        z_size = engine.input_width("sample")
        if getattr(engine, "conditional", False):
            # Conditional bundle: the probe draws BASE-z latents and the
            # gate supplies a cycling one-hot class block (every class
            # represented) — uniform noise in the embedding slots would
            # probe off the trained input manifold and score garbage.
            classes = engine.class_count
            z_size = engine.latent_width("sample")
            labels = np.arange(self.num_samples) % classes
            onehot = np.eye(classes, dtype=np.float32)[labels]

            def sample_fn(z, _onehot=onehot):  # noqa: F811
                return engine.run(
                    "sample",
                    np.concatenate([z, _onehot[: z.shape[0]]], axis=1),
                )

        return quality_probe(
            sample_fn,
            self.features,
            z_size=z_size,
            num_samples=self.num_samples,
            seed=self.seed,
            classify_fn=classify_fn,
            labels=self.labels,
            feature_fn=self.feature_fn,
        )

    def _incumbent_probe(self, incumbent) -> dict:
        key = (incumbent, getattr(incumbent, "generation", None))
        if (self._incumbent_cache is not None
                and self._incumbent_cache[0] == key):
            return self._incumbent_cache[1]
        result = self.probe(incumbent)
        self._incumbent_cache = (key, result)
        return result

    # -- the gate --------------------------------------------------------
    def dataset_mismatch(self, engine) -> Optional[str]:
        """The rejection reason when ``engine``'s manifest declares a zoo
        dataset other than this gate's real rows, else None. Pre-zoo
        bundles (no scenario) and gates built without a ``dataset`` are
        never mismatched — the check is additive over legacy behavior."""
        if self.dataset is None:
            return None
        scenario = getattr(engine, "scenario", None)
        declared = scenario.get("dataset") if scenario else None
        if declared is not None and declared != self.dataset:
            return (f"candidate bundle trains dataset {declared!r} but the "
                    f"gate's real rows are {self.dataset!r} — refusing to "
                    f"FID-score across datasets")
        return None

    def evaluate(self, candidate, incumbent) -> CanaryDecision:
        """Admit or reject ``candidate`` relative to ``incumbent`` — the
        measurement here, the decision in :func:`compare_probes` (shared
        with the fleet manager's sidecar canary). A candidate declaring a
        different zoo dataset than the gate's real rows fails CLOSED,
        before any probe runs."""
        mismatch = self.dataset_mismatch(candidate)
        if mismatch is not None:
            return CanaryDecision(
                passed=False, reason=mismatch, candidate={}, incumbent={})
        inc = self._incumbent_probe(incumbent)
        cand = self.probe(candidate)
        decision = compare_probes(cand, inc, self.thresholds)
        if decision.passed:
            # the admitted candidate is about to BECOME the incumbent:
            # roll the cache forward so the next reload reuses its probe
            # (one candidate probe per reload) and the retired engine's
            # strong reference — params, executables, staging pools — is
            # released instead of pinned until the next evaluate
            self._incumbent_cache = (
                (candidate, getattr(candidate, "generation", None)), cand)
        return decision
