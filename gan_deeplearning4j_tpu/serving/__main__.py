"""Server CLI — ``python -m gan_deeplearning4j_tpu.serving [flags]``.

Loads a serving bundle (``serving.json`` published by
``GanExperiment.publish_for_serving``) or explicit checkpoint zips and
serves the HTTP JSON API until interrupted. Examples::

    python -m gan_deeplearning4j_tpu.serving --bundle output/serving
    python -m gan_deeplearning4j_tpu.serving \\
        --generator output/mnist_gen_model.zip \\
        --classifier output/mnist_CV_model.zip \\
        --feature-vertex dis_dense_layer_6 --port 8000
"""

from __future__ import annotations

import argparse
import logging
import sys

from gan_deeplearning4j_tpu.serving.engine import DEFAULT_BUCKETS, ServingEngine
from gan_deeplearning4j_tpu.serving.service import InferenceService, serve_forever


def _parse_buckets(text: str):
    try:
        return tuple(int(b) for b in text.split(",") if b.strip())
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"buckets must be comma-separated ints, got {text!r}"
        )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gan_deeplearning4j_tpu.serving",
        description="Batched inference server for the trained GAN artifacts",
    )
    p.add_argument("--bundle", default=None,
                   help="serving bundle directory (contains serving.json)")
    p.add_argument("--generator", default=None, help="generator checkpoint zip")
    p.add_argument("--classifier", default=None, help="classifier checkpoint zip")
    p.add_argument("--feature-vertex", default=None,
                   help="classifier vertex served by /v1/features")
    p.add_argument("--buckets", type=_parse_buckets,
                   default=DEFAULT_BUCKETS,
                   help="padded batch ladder, e.g. 1,8,32,128")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--max-latency", type=float, default=0.005,
                   help="micro-batch trigger: max seconds a request waits "
                        "for batch-mates")
    p.add_argument("--max-queue", type=int, default=256,
                   help="bound on queued requests before shedding")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="default per-request deadline (seconds)")
    p.add_argument("--replicas", default="all",
                   help="devices to route batches across: an int, or 'all' "
                        "for every local device (default)")
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="bound on dispatched-but-unfinished flushes "
                        "(default: 2 per replica)")
    p.add_argument("--warmup", choices=("eager", "sync", "off"),
                   default="eager",
                   help="'eager' compiles the ladder on a background thread "
                        "(serve immediately, /healthz reports 'warming'); "
                        "'sync' blocks startup until warm; 'off' compiles "
                        "lazily (first request per bucket pays it)")
    p.add_argument("--compilation-cache", default=None, metavar="DIR",
                   help="persistent XLA compile cache dir so process "
                        "restarts reuse AOT artifacts (default: "
                        "$GDT_COMPILATION_CACHE / repo .jax_cache policy)")
    p.add_argument("--reload-store", default=None, metavar="DIR",
                   help="zero-downtime reload plane (docs/DEPLOY.md): "
                        "watch this checkpoint-store root for newer "
                        "digest-valid serving generations and swap them in "
                        "live; without --bundle/--generator the FIRST "
                        "valid generation there is the initial model")
    p.add_argument("--reload-poll", type=float, default=2.0,
                   help="reload-plane poll interval in seconds")
    p.add_argument("--reload-wait", type=float, default=120.0,
                   help="with --reload-store and no --bundle: seconds to "
                        "wait for the first valid serving generation")
    p.add_argument("--canary-data", default=None, metavar="NPZ",
                   help="npz with 'features' (and optionally 'labels') "
                        "arrays for the reload canary gate; omitted = no "
                        "quality gate (digest verification still applies)")
    p.add_argument("--canary-samples", type=int, default=256,
                   help="seeded probe batch size for the canary gate")
    p.add_argument("--canary-feature", choices=("raw", "dis_features"),
                   default="raw",
                   help="FID feature space for the canary probes: 'raw' "
                        "compares raw sample rows; 'dis_features' embeds "
                        "both sides in the discriminator-feature space of "
                        "the BOOT bundle's classifier at its feature "
                        "vertex (pinned at startup so every candidate is "
                        "scored in one space — docs/DEPLOY.md)")
    p.add_argument("--canary-fid-ratio", type=float, default=1.5,
                   help="reject a candidate whose probe FID exceeds "
                        "incumbent × ratio + slack")
    p.add_argument("--canary-fid-slack", type=float, default=10.0,
                   help="additive FID slack (keeps near-zero incumbents "
                        "from making the ratio test vacuous-strict)")
    p.add_argument("--canary-acc-drop", type=float, default=0.05,
                   help="reject a candidate whose classifier accuracy "
                        "drops more than this below the incumbent")
    p.add_argument("--telemetry", action="store_true",
                   help="enable span tracing (GET /debug/spans exports a "
                        "Chrome trace; also honored via "
                        "GDT_TELEMETRY=trace); metrics are always on")
    p.add_argument("--debug-artifacts", default=None, metavar="DIR",
                   help="where POST /debug/trace dumps jax.profiler device "
                        "captures (default: $GDT_TRACE_DIR or "
                        "./artifacts/device_traces)")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    from gan_deeplearning4j_tpu.telemetry.trace import TRACER, configure_from_env

    if args.telemetry:
        TRACER.enable()
    else:
        configure_from_env()
    from gan_deeplearning4j_tpu.runtime.environment import enable_compilation_cache

    cache_dir = enable_compilation_cache(args.compilation_cache)
    if cache_dir:
        logging.getLogger(__name__).info("compilation cache: %s", cache_dir)
    replicas = None if args.replicas == "all" else int(args.replicas)
    watcher = None
    if args.reload_store is not None:
        from gan_deeplearning4j_tpu.deploy import StoreWatcher
        from gan_deeplearning4j_tpu.resilience import CheckpointStore

        watcher = StoreWatcher(store=CheckpointStore(args.reload_store))
    canary_bundle = None  # bundle dir a dis-feature classifier resolves from
    canary_classifier = None  # (checkpoint, vertex) for dis-feature probes
    if args.bundle is not None:
        engine = ServingEngine.from_bundle(
            args.bundle, buckets=args.buckets, replicas=replicas
        )
        canary_bundle = args.bundle
    elif args.generator or args.classifier:
        engine = ServingEngine.from_checkpoints(
            generator=args.generator,
            classifier=args.classifier,
            buckets=args.buckets,
            feature_vertex=args.feature_vertex,
            replicas=replicas,
        )
        if args.classifier and args.feature_vertex:
            canary_classifier = (args.classifier, args.feature_vertex)
    elif watcher is not None:
        # bootstrap from the watched store: the first valid serving
        # generation is the initial model (a trainer may still be warming
        # up toward its first publish — wait, bounded)
        import time as _time

        log = logging.getLogger(__name__)
        deadline = _time.monotonic() + args.reload_wait
        candidate = None
        while candidate is None:
            candidate = watcher.poll_once()
            if candidate is None:
                if _time.monotonic() >= deadline:
                    log.error("no valid serving generation appeared in %s "
                              "within %.0fs", args.reload_store,
                              args.reload_wait)
                    return 1
                _time.sleep(0.5)
        log.info("initial bundle: generation %s (%s)",
                 candidate.generation, candidate.path)
        engine = ServingEngine.from_bundle(
            candidate.path, buckets=args.buckets, replicas=replicas
        )
        canary_bundle = candidate.path
    else:
        p.error("need --bundle, --generator/--classifier, or --reload-store")
        return 2  # unreachable; argparse exits
    service = InferenceService(
        engine,
        max_latency=args.max_latency,
        max_queue=args.max_queue,
        default_timeout=args.timeout,
        warmup={"eager": "eager", "sync": "sync", "off": False}[args.warmup],
        pipeline_depth=args.pipeline_depth,
        artifacts_dir=args.debug_artifacts,
    )
    controller = None
    if watcher is not None:
        from gan_deeplearning4j_tpu.deploy import CanaryGate, CanaryThresholds
        from gan_deeplearning4j_tpu.deploy import ReloadController
        import numpy as np

        canary = None
        if args.canary_data:
            feature_fn = None
            if args.canary_feature == "dis_features":
                if canary_classifier is None and canary_bundle is not None:
                    # resolved lazily: only this branch needs the manifest
                    from gan_deeplearning4j_tpu.deploy.canary import (
                        classifier_from_bundle,
                    )

                    canary_classifier = classifier_from_bundle(canary_bundle)
                if canary_classifier is None:
                    p.error("--canary-feature dis_features needs a boot "
                            "bundle (or --classifier/--feature-vertex) "
                            "serving a dis-feature vertex")
                from gan_deeplearning4j_tpu.deploy import (
                    feature_fn_from_checkpoint,
                )

                feature_fn = feature_fn_from_checkpoint(*canary_classifier)
            with np.load(args.canary_data) as npz:
                features = npz["features"]
                labels = npz["labels"] if "labels" in npz.files else None
            canary = CanaryGate(
                features, labels,
                num_samples=min(args.canary_samples, features.shape[0]),
                feature_fn=feature_fn,
                thresholds=CanaryThresholds(
                    fid_ratio_max=args.canary_fid_ratio,
                    fid_slack=args.canary_fid_slack,
                    accuracy_drop_max=args.canary_acc_drop,
                ),
            )
        controller = ReloadController(
            service, watcher, canary=canary,
            poll_interval=args.reload_poll,
        )
        service.attach_reloader(controller)
        controller.start()
    try:
        serve_forever(service, args.host, args.port)
    finally:
        if controller is not None:
            controller.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
