"""Traffic-shaped bucket ladders — learn the AOT ladder from live sizes.

The serving engine compiles one executable per (kind, bucket) and pads
every request up to its bucket (docs/SERVING.md). Since PR 3 the ladder
has been the hard-coded ``1/8/32/128`` guess, so padding waste is shaped
by a config default instead of by traffic. This module closes that loop:

- :class:`SizeHistogram` — a bounded, thread-safe per-kind size
  histogram the micro-batcher records each ASSEMBLED flush into (one
  dict increment per flush; no allocation once a size has been seen).
  Flush sizes — not submit sizes — are what the engine pads: under
  concurrency the batcher coalesces requests, and a ladder solved from
  per-request sizes measurably regresses when coalesced batches fall in
  the gaps between its buckets. Exported via ``/metrics`` and persisted
  into the bundle manifest so the NEXT generation boots with learned
  buckets.
- :func:`solve_ladder` — an exact dynamic program over the observed
  sizes choosing ``<= budget`` buckets that minimize expected
  padded-rows waste. The incumbent's top bucket is always kept: it is
  the chunking contract (``max_batch``, the bulk-lane slab width, and
  the "chunks of top are waste-free" identity all key on it), so a
  learned ladder never changes what a request larger than top costs.
- :func:`expected_waste` — the objective itself, reusable by benches and
  tests as the oracle for what the engine's chunker will pad.
- manifest helpers (``write_ladder_block`` / ``manifest_ladder`` /
  ``manifest_histogram``) — the ladder travels WITH the bundle in
  ``serving.json`` (same atomic-rename write as the quant cost block),
  so every loader (``from_bundle``, mux ``build_engine``, fleet
  workers) resolves the same learned ladder without extra flags.

Waste model (what the DP minimizes): the engine's chunker takes
``n = min(top, remaining)`` slices and pads each to the smallest bucket
``>= n``. A flush of ``s`` rows therefore wastes nothing on its full
``top``-chunks and ``bucket(r) - r`` rows on the remainder
``r = s % top`` (``r = s`` when ``s < top``; ``r == 0`` wastes
nothing). Folding every observed size to its remainder reduces the
problem to: given remainder counts ``c_r`` over ``r in [1, top)``,
choose ``<= budget - 1`` cut sizes (plus the mandatory ``top``) to
minimize ``sum_r c_r * (bucket(r) - r)``. An optimal ladder only ever
places buckets AT observed remainders (lowering a bucket to the next
observed size below it never increases waste), so the exact optimum is
an O(m^2 * budget) DP over the ``m`` distinct remainders — the same
per-layer micro-batching split that mu-cuDNN solves with DP under a
workspace budget (PAPERS.md), with compile count playing the role of
workspace.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, Mapping, Optional, Sequence, Tuple

__all__ = [
    "SizeHistogram",
    "solve_ladder",
    "expected_waste",
    "write_ladder_block",
    "manifest_ladder",
    "manifest_histogram",
]

#: distinct sizes tracked per kind before overflow folding kicks in.
#: Request sizes are small integers (rows per request); 256 distinct
#: values per kind is far past anything the batcher has ever seen, and
#: bounds both memory and the DP's input width.
DEFAULT_MAX_SIZES = 256


class SizeHistogram:
    """Bounded per-kind request-size counts, safe under the batcher's
    submit concurrency.

    Overflow policy (documented because it biases the solver): once a
    kind tracks ``max_sizes`` distinct sizes, an unseen size is folded
    UP to the smallest tracked size above it — conservative for the
    padding objective (the solver then plans for a slightly larger
    request, never a smaller one). A size above every tracked size folds
    into the largest tracked size: it undercounts rows but keeps the
    table bounded, and sizes that large are chunk-dominated anyway.
    """

    __slots__ = ("_lock", "_counts", "_max_sizes", "_folded")

    def __init__(self, max_sizes: int = DEFAULT_MAX_SIZES):
        if max_sizes < 1:
            raise ValueError("max_sizes must be >= 1")
        self._lock = threading.Lock()
        self._counts: Dict[str, Dict[int, int]] = {}
        self._max_sizes = int(max_sizes)
        self._folded = 0  # records that hit the overflow fold

    def record(self, kind: str, n: int) -> None:
        """Count one request of ``n`` rows for ``kind`` (hot path)."""
        n = int(n)
        if n < 1:
            return
        with self._lock:
            sizes = self._counts.get(kind)
            if sizes is None:
                sizes = self._counts[kind] = {}
            if n in sizes:
                sizes[n] += 1
                return
            if len(sizes) < self._max_sizes:
                sizes[n] = 1
                return
            # overflow: fold up to the nearest tracked size (see class
            # docstring), else into the largest tracked size
            above = [s for s in sizes if s >= n]
            target = min(above) if above else max(sizes)
            sizes[target] += 1
            self._folded += 1

    def merge(self, snapshot: Mapping[str, Mapping]) -> None:
        """Fold another histogram's snapshot in (adoption carry-forward,
        manifest restore). Accepts string size keys — JSON round-trips
        them that way."""
        for kind, sizes in (snapshot or {}).items():
            if not isinstance(sizes, Mapping):
                continue
            for s, c in sizes.items():
                try:
                    s, c = int(s), int(c)
                except (TypeError, ValueError):
                    continue
                if s >= 1 and c >= 1:
                    self._merge_one(str(kind), s, c)

    def _merge_one(self, kind: str, n: int, c: int) -> None:
        with self._lock:
            sizes = self._counts.setdefault(kind, {})
            if n in sizes or len(sizes) < self._max_sizes:
                sizes[n] = sizes.get(n, 0) + c
                return
            above = [s for s in sizes if s >= n]
            target = min(above) if above else max(sizes)
            sizes[target] += c
            self._folded += 1

    def snapshot(self) -> Dict[str, Dict[int, int]]:
        """``{kind: {size: count}}`` — a deep copy, sorted by size."""
        with self._lock:
            return {
                kind: {s: sizes[s] for s in sorted(sizes)}
                for kind, sizes in self._counts.items()
            }

    def merged(self) -> Dict[int, int]:
        """Cross-kind ``{size: count}`` — the solver's input (every kind
        shares one ladder per engine, so waste pools across kinds)."""
        out: Dict[int, int] = {}
        with self._lock:
            for sizes in self._counts.values():
                for s, c in sizes.items():
                    out[s] = out.get(s, 0) + c
        return {s: out[s] for s in sorted(out)}

    def total(self) -> int:
        with self._lock:
            return sum(c for sizes in self._counts.values()
                       for c in sizes.values())

    def stats(self) -> dict:
        """The ``/metrics`` export block."""
        snap = self.snapshot()
        return {
            "total": sum(c for sizes in snap.values()
                         for c in sizes.values()),
            "folded": self._folded,
            "kinds": {
                kind: {str(s): c for s, c in sizes.items()}
                for kind, sizes in snap.items()
            },
        }


def _fold_counts(counts: Mapping, top: int) -> Dict[int, int]:
    """Observed sizes -> remainder counts in ``[1, top)`` (full
    ``top``-chunks are waste-free and drop out of the objective)."""
    folded: Dict[int, int] = {}
    for s, c in counts.items():
        s, c = int(s), int(c)
        if s < 1 or c < 1:
            continue
        r = s % top if s >= top else s
        if r == 0:
            continue
        folded[r] = folded.get(r, 0) + c
    return folded


def expected_waste(counts: Mapping, buckets: Sequence[int]) -> int:
    """Padded rows the engine's chunker will waste serving ``counts``
    (``{size: count}``) on ``buckets`` — the solver's exact objective,
    and the bench's oracle."""
    ladder = sorted(set(int(b) for b in buckets))
    if not ladder or ladder[0] < 1:
        raise ValueError(f"bad ladder {buckets!r}")
    top = ladder[-1]
    waste = 0
    for r, c in _fold_counts(counts, top).items():
        b = ladder[bisect_left(ladder, r)]  # smallest bucket >= r < top
        waste += c * (b - r)
    return waste


def solve_ladder(counts: Mapping, budget: int, *,
                 top: Optional[int] = None) -> Tuple[int, ...]:
    """Choose ``<= budget`` buckets minimizing expected padded-rows
    waste over ``counts`` (``{size: count}``), always including ``top``.

    ``top`` defaults to the largest observed size; pass the incumbent
    ladder's top bucket to preserve the chunking contract (ISSUE 19 —
    ``max_batch`` and the bulk lane key on it). Deterministic: ties
    break toward fewer, then smaller, buckets. ``budget=1`` degenerates
    to ``(top,)``; an empty histogram returns ``(top,)`` (nothing to
    learn — callers keep their incumbent ladder instead).
    """
    if budget < 1:
        raise ValueError("budget must be >= 1")
    clean = {int(s): int(c) for s, c in (counts or {}).items()
             if int(s) >= 1 and int(c) >= 1}
    if top is None:
        if not clean:
            raise ValueError("empty histogram and no top bucket given")
        top = max(clean)
    top = int(top)
    if top < 1:
        raise ValueError(f"top bucket must be >= 1, got {top}")

    folded = _fold_counts(clean, top)
    sizes = sorted(folded)
    m = len(sizes)
    k_free = min(budget - 1, m)
    if k_free >= m:
        # a bucket at every observed remainder: zero waste
        return tuple(sizes + [top])
    if k_free == 0:
        return (top,)

    weight = [folded[s] for s in sizes]
    # prefix sums: pc[i] = sum(weight[:i]), pw[i] = sum(w*s over [:i])
    pc = [0] * (m + 1)
    pw = [0] * (m + 1)
    for i, (s, w) in enumerate(zip(sizes, weight)):
        pc[i + 1] = pc[i] + w
        pw[i + 1] = pw[i] + w * s

    def span_cost(j: int, i: int) -> int:
        # bucket at sizes[i] covering sizes[j..i] (0-based, inclusive)
        return sizes[i] * (pc[i + 1] - pc[j]) - (pw[i + 1] - pw[j])

    def tail_cost(i: int) -> int:
        # sizes[i+1..m-1] fall through to top
        return top * (pc[m] - pc[i + 1]) - (pw[m] - pw[i + 1])

    INF = float("inf")
    # dp[k][i]: min waste covering sizes[0..i] with exactly k buckets,
    # the k-th placed at sizes[i]
    dp = [[INF] * m for _ in range(k_free + 1)]
    parent = [[-1] * m for _ in range(k_free + 1)]
    for i in range(m):
        dp[1][i] = span_cost(0, i)
    for k in range(2, k_free + 1):
        dpk, dpk1 = dp[k], dp[k - 1]
        par = parent[k]
        for i in range(k - 1, m):
            best, arg = INF, -1
            for j in range(k - 2, i):
                if dpk1[j] is INF:
                    continue
                cand = dpk1[j] + span_cost(j + 1, i)
                if cand < best:  # strict: smallest j wins ties
                    best, arg = cand, j
            dpk[i], par[i] = best, arg

    # pick (k, i): fewer buckets win ties, then smaller last-bucket
    best, best_k, best_i = INF, 0, -1
    for k in range(1, k_free + 1):
        for i in range(m):
            total = dp[k][i] + tail_cost(i)
            if total < best:
                best, best_k, best_i = total, k, i
    if best_i < 0:  # unreachable (m >= 1 here), but stay total
        return (top,)

    picks = []
    k, i = best_k, best_i
    while i >= 0 and k >= 1:
        picks.append(sizes[i])
        i = parent[k][i]
        k -= 1
    ladder = sorted(set(picks) | {top})
    return tuple(ladder)


# -- manifest persistence ----------------------------------------------------
# The ladder block rides the bundle manifest (serving.json) next to the
# quant cost block, via the same atomic temp+rename write, so watchers
# never see a torn manifest and every loader resolves one source of
# truth. Imports of quant.variants stay lazy: quant.cost imports the
# serving engine, and the engine lazily imports THIS module.

LADDER_BLOCK = "ladder"


def write_ladder_block(bundle_dir: str, buckets: Sequence[int], *,
                       histogram: Optional[Mapping] = None,
                       solved_from: Optional[dict] = None) -> dict:
    """Persist a learned ladder (and optionally the histogram it was
    solved from) into the bundle manifest. Returns the block written."""
    from gan_deeplearning4j_tpu.quant.variants import (
        read_bundle_manifest, write_bundle_manifest)

    ladder = sorted(set(int(b) for b in buckets))
    if not ladder or ladder[0] < 1:
        raise ValueError(f"bad ladder {buckets!r}")
    block: dict = {"buckets": ladder}
    if histogram:
        block["histogram"] = {
            str(kind): {str(s): int(c) for s, c in sizes.items()}
            for kind, sizes in histogram.items()
        }
    if solved_from:
        block["solved_from"] = dict(solved_from)
    manifest = read_bundle_manifest(bundle_dir)
    manifest[LADDER_BLOCK] = block
    write_bundle_manifest(bundle_dir, manifest)
    return block


def _read_block(bundle_dir: str) -> Optional[dict]:
    from gan_deeplearning4j_tpu.quant.variants import read_bundle_manifest

    try:
        manifest = read_bundle_manifest(bundle_dir)
    except (OSError, ValueError):
        return None
    block = manifest.get(LADDER_BLOCK)
    return block if isinstance(block, dict) else None


def manifest_ladder(bundle_dir: str) -> Optional[Tuple[int, ...]]:
    """The bundle's learned ladder, or None when absent/malformed (a
    malformed block must fall back to defaults, never fail a load)."""
    block = _read_block(bundle_dir)
    if not block:
        return None
    raw = block.get("buckets")
    if not isinstance(raw, (list, tuple)) or not raw:
        return None
    try:
        ladder = tuple(sorted(set(int(b) for b in raw)))
    except (TypeError, ValueError):
        return None
    if ladder[0] < 1:
        return None
    return ladder


def manifest_histogram(bundle_dir: str) -> Optional[Dict[str, Dict[int, int]]]:
    """The histogram persisted alongside the ladder — seeds a new
    generation's live histogram so learning compounds across reloads."""
    block = _read_block(bundle_dir)
    if not block:
        return None
    raw = block.get("histogram")
    if not isinstance(raw, dict):
        return None
    out: Dict[str, Dict[int, int]] = {}
    for kind, sizes in raw.items():
        if not isinstance(sizes, dict):
            continue
        clean = {}
        for s, c in sizes.items():
            try:
                s, c = int(s), int(c)
            except (TypeError, ValueError):
                continue
            if s >= 1 and c >= 1:
                clean[s] = c
        if clean:
            out[str(kind)] = clean
    return out or None
