"""ServingEngine — checkpoint-backed executor with a padded bucket ladder.

Loads serializer checkpoints (``utils/serializer.read_model`` — topology +
params, no training code needed), pins the weights on device ONCE per
replica, and pre-compiles one XLA executable per (request kind, batch
bucket, replica) via jit's AOT path (``lower().compile()``). Requests are
padded up to the smallest bucket and sliced back, so an arbitrary request
size NEVER triggers a fresh compile at serve time: ``warmup()`` compiles
the full ladder before the first request (the service does this at
construction, eagerly in a background thread if asked), and
``serve_compile_counts`` proves the count of post-warmup compiles stays 0
— with free-running shapes every new batch size would stall a request
tail for seconds of XLA compilation (the recompilation hazard jaxlint
JG004 polices in training code, recurring here as a serving tail-latency
cliff).

The serve fast path (docs/SERVING.md "Fast path"):

- **staged assembly** — padding is not a per-call ``np.zeros`` +
  ``np.concatenate``: each (kind, bucket) keeps a small pool of reusable
  pinned staging buffers whose pad tail is maintained at zero via a
  high-water mark, so assembling a flush is one memcpy per rider and at
  most one memset of the shrink delta, then a single ``device_put``.
  (True device-side padding of an ``(n, width)`` transfer would need an
  executable specialized per ``n`` — unbounded compiles, the exact hazard
  the ladder exists to kill — so the pad lives in the pinned host buffer
  and the device sees only bucket shapes.)
- **dispatch/finalize split** — ``dispatch()`` stages, transfers, and
  launches the AOT executable without waiting for the result (XLA
  dispatch is async); ``finalize()`` blocks, slices the padding off, and
  recycles the staging buffer. The micro-batcher runs the two halves on
  different threads so host assembly of batch N+1 overlaps device
  execution of batch N.
- **multi-replica routing** — with ``replicas > 1`` every (kind, bucket)
  executable is compiled once per replica device and each flush is routed
  to the least-loaded replica; oversized single-caller batches can
  additionally ride one mesh-sharded bulk executable that splits a
  ``top_bucket × replicas`` slab across all replicas at once.

Request kinds (SURVEY §0 — the trained artifacts, not the loop):

- ``sample``:   z (n, z_size)        -> generator images (n, num_features)
- ``classify``: x (n, num_features)  -> class probabilities (n, num_classes)
- ``features``: x (n, num_features)  -> discriminator-feature activations
  at the transfer classifier's feature vertex (mnist: ``dis_dense_layer_6``)
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import TRACER

DEFAULT_BUCKETS = (1, 8, 32, 128)

#: staging buffers kept per (kind, bucket) — enough for a deep pipeline
#: window without ever allocating on the hot path
_POOL_LIMIT = 4


class _StagingBuf:
    """A reusable pinned host buffer of bucket shape whose tail is kept at
    zero. ``high_water`` is the largest row count ever written: rows past
    it are known-zero, so a smaller flush only memsets the shrink delta
    ``[n, high_water)`` instead of the whole pad region."""

    __slots__ = ("arr", "high_water")

    def __init__(self, bucket: int, width: int):
        self.arr = np.zeros((bucket, width), np.float32)
        self.high_water = 0

    def reset_tail(self, n: int) -> None:
        if self.high_water > n:
            self.arr[n:self.high_water] = 0.0
        # rows past n are now zero either way (freshly zeroed above, or
        # zero since construction) — n IS the new high water; a monotone
        # max would re-memset the full pad region on every small flush
        # after one large one
        self.high_water = n


class _Flight:
    """One dispatched flush: the in-flight device computation plus what
    ``finalize`` needs to slice, recycle, and account it. ``lane`` is the
    replica the flush was routed to — the batcher's per-replica completion
    lanes key on it, so one replica's slow finalize never head-of-line
    blocks another replica's finished work (multi-chunk flights use the
    first chunk's replica; bulk-lane flights ride lane 0)."""

    __slots__ = ("kind", "total", "parts", "lane")

    def __init__(self, kind: str, total: int, parts: list, lane: int = 0):
        self.kind = kind
        self.total = total
        # parts: (device_out, n_real_rows, staging_buf_or_None, replica_or_None)
        self.parts = parts
        self.lane = lane


class ServingEngine:
    """Model-backed executor: ``run(kind, rows) -> rows``, or the async
    pair ``dispatch(kind, rows_list) -> flight`` / ``finalize(flight)``.

    ``models`` maps role ("generator"/"classifier") to a loaded
    ``(ComputationGraph, params)`` pair. Thread-safe: AOT executables are
    compiled under a lock (warmup may race the serve path), and the
    staging pool is checked out/in under the same lock."""

    def __init__(
        self,
        models: Dict[str, Tuple[object, dict]],
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        feature_vertex: Optional[str] = None,
        replicas: Optional[int] = 1,
        generation: Optional[int] = None,
        export_gauge: bool = True,
        staging_pool=None,
        precision: Optional[str] = None,
        scenario: Optional[dict] = None,
    ):
        import jax

        from gan_deeplearning4j_tpu.runtime.dtype import parse_compute_dtype

        if not models:
            raise ValueError("ServingEngine needs at least one model")
        #: the bundle manifest's zoo scenario block (zoo/manifest.py) —
        #: declares dataset identity and conditioning. A conditional
        #: scenario makes ``sample?class=k`` legal (serving/service.py
        #: appends the one-hot embedding); None = pre-zoo bundle,
        #: unconditional. Kept as the raw dict so the engine layer has no
        #: zoo import; ``scenario_manifest()`` parses on demand.
        self.scenario = dict(scenario) if scenario else None
        #: store generation of the loaded bundle (None for bare-checkpoint
        #: loads) — the version the reload plane keys on; /healthz and
        #: /metrics surface it so an operator can see WHICH model serves
        self.generation = generation
        #: the bundle manifest's declared precision ("bf16"/"int8"/None =
        #: fp32; docs/QUANT.md). bf16 additionally selects the compute
        #: dtype the AOT executables are traced under, so storage and
        #: matmul precision drop together; int8 needs no compute scope —
        #: the quantized layers carry their own dtypes in the graph.
        self.precision = precision
        self._compute_dtype = (parse_compute_dtype("bf16")
                               if precision == "bf16" else None)
        buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"invalid bucket ladder {buckets!r}")
        self.buckets = buckets
        self.feature_vertex = feature_vertex

        devices = jax.local_devices()
        if replicas is None:
            replicas = len(devices)
        if not 1 <= replicas <= len(devices):
            raise ValueError(
                f"replicas={replicas} but {len(devices)} local device(s) "
                f"are available"
            )
        self._devices = tuple(devices[:replicas])

        # weights cross to each replica once, here — never per request
        self._graphs = {role: graph for role, (graph, _) in models.items()}
        self._params = {
            role: [jax.device_put(params, d) for d in self._devices]
            for role, (_, params) in models.items()
        }

        self._kinds: Dict[str, Tuple[str, object]] = {}  # kind -> (role, fn)
        if "generator" in models:
            gen = self._graphs["generator"]
            # flatten NHWC image outputs to (n, features): the wire contract
            # is rows, matching the reference's flat CSV exports
            self._kinds["sample"] = (
                "generator",
                lambda p, z: gen.output(p, z, train=False).reshape(
                    (z.shape[0], -1)
                ),
            )
        if "classifier" in models:
            cv = self._graphs["classifier"]
            self._kinds["classify"] = (
                "classifier",
                lambda p, x: cv.output(p, x, train=False),
            )
            if feature_vertex is not None:
                if feature_vertex not in {v.name for v in cv.vertices}:
                    raise ValueError(
                        f"feature vertex {feature_vertex!r} is not a vertex of "
                        f"the classifier graph"
                    )
                self._kinds["features"] = (
                    "classifier",
                    lambda p, x: cv.feed_forward(p, x, train=False)[feature_vertex],
                )

        self._in_width = {
            kind: self._graphs[role].input_types[0].features
            for kind, (role, _) in self._kinds.items()
        }
        if self.conditional and "sample" in self._in_width:
            declared = int(self.scenario.get("z_size", 0)) + self.class_count
            if self._in_width["sample"] != declared:
                raise ValueError(
                    f"conditional bundle declares z_size+classes = {declared} "
                    f"but the generator takes {self._in_width['sample']} "
                    f"inputs — manifest and checkpoint disagree"
                )
        self._compiled: Dict[Tuple[str, int, int], object] = {}
        self._bulk: Dict[str, object] = {}  # kind -> mesh-sharded executable
        self._params_mesh: Dict[str, object] = {}
        self._batch_sharding = None
        self._compile_counts: Dict[str, int] = {k: 0 for k in self._kinds}
        self._serve_compiles: Dict[str, int] = {k: 0 for k in self._kinds}
        # padded-rows waste ledger: rows the chunker padded past the real
        # request rows, per kind — the number the learned ladder exists
        # to shrink (serving/ladder.py); the replay bench reads it as the
        # measured counterpart of expected_waste()
        self._padded_waste: Dict[str, int] = {k: 0 for k in self._kinds}
        # telemetry registry mirrors of the compile ledger + routing
        # (docs/OBSERVABILITY.md): the dict above stays the per-engine
        # invariant the bench asserts; the registry series are what a
        # scraper and the BENCH snapshot read
        _registry = get_registry()
        _compiles = _registry.counter(
            "serve_engine_compiles_total",
            "XLA compiles per request kind (warmup + serve-time)",
            labelnames=("kind",),
        )
        _serve_c = _registry.counter(
            "serve_engine_serve_compiles_total",
            "post-warmup compiles per kind (fast-path contract: stays 0)",
            labelnames=("kind",),
        )
        self._c_compiles = {k: _compiles.labels(kind=k) for k in self._kinds}
        self._c_serve_compiles = {
            k: _serve_c.labels(kind=k) for k in self._kinds
        }
        _waste = _registry.counter(
            "serve_padded_rows_wasted_total",
            "rows padded past the request rows per kind (the learned "
            "ladder's objective — serving/ladder.py)",
            labelnames=("kind",),
        )
        self._c_waste = {k: _waste.labels(kind=k) for k in self._kinds}
        _dispatches = _registry.counter(
            "serve_engine_dispatches_total",
            "flush dispatches routed per replica",
            labelnames=("replica",),
        )
        self._c_dispatches = [
            _dispatches.labels(replica=str(i)) for i in range(replicas)
        ]
        self._g_generation = _registry.gauge(
            "serving_generation",
            "store generation of the served bundle (-1 = unversioned)",
        )
        # a reload-plane CANDIDATE engine is constructed (and warmed, and
        # canaried) while another engine is still live — it must not
        # claim the process-wide gauge until it actually serves
        # (export_gauge=False; the reloader calls export_generation()
        # at the swap)
        if export_gauge:
            self.export_generation()
        # staging buffers: private per-engine pools by default; the mux
        # plane passes ONE shared pool (serving/mux SharedStagingPool)
        # so N resident engines share buffers instead of each keeping
        # its own — residency cost scales sub-linearly in variants
        # (buffers are keyed (bucket, width), model-agnostic bytes)
        self._shared_staging = staging_pool
        self._staging: Dict[Tuple[str, int], List[_StagingBuf]] = {}
        self._outstanding = [0] * replicas  # in-flight flushes per replica
        self._dispatches = [0] * replicas
        self._rr = 0  # round-robin tiebreak cursor
        self._warmed = False
        self._warm_thread: Optional[threading.Thread] = None
        self._warm_error: Optional[BaseException] = None
        # _lock: cheap shared state (staging pool, routing, counters);
        # _compile_lock: serializes XLA compiles only, so warmup compiling
        # the ladder never blocks the cached-executable serve path
        self._lock = threading.Lock()
        self._compile_lock = threading.Lock()

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_checkpoints(
        cls,
        generator: Optional[str] = None,
        classifier: Optional[str] = None,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        feature_vertex: Optional[str] = None,
        replicas: Optional[int] = 1,
        generation: Optional[int] = None,
        export_gauge: bool = True,
        staging_pool=None,
        precision: Optional[str] = None,
        scenario: Optional[dict] = None,
    ) -> "ServingEngine":
        """Restore from serializer checkpoint zips. Updater state is never
        loaded — a serving replica has no optimizer."""
        from gan_deeplearning4j_tpu.utils.serializer import read_model

        models = {}
        with TRACER.span("serve.engine.restore", generation=generation):
            for role, path in (("generator", generator),
                               ("classifier", classifier)):
                if path is None:
                    continue
                graph, params, _, _ = read_model(path, load_updater=False)
                models[role] = (graph, params)
        return cls(models, buckets=buckets, feature_vertex=feature_vertex,
                   replicas=replicas, generation=generation,
                   export_gauge=export_gauge, staging_pool=staging_pool,
                   precision=precision, scenario=scenario)

    @classmethod
    def from_bundle(
        cls, directory: str, *, buckets: Optional[Sequence[int]] = None,
        replicas: Optional[int] = 1, export_gauge: bool = True,
        staging_pool=None,
    ) -> "ServingEngine":
        """Load a ``serving.json`` bundle published by
        ``GanExperiment.publish_for_serving``.

        ``buckets=None`` (the default) resolves the bundle's LEARNED
        ladder when the manifest carries one (``serving/ladder.py`` —
        solved from recorded traffic and persisted at reload/publish
        time), falling back to :data:`DEFAULT_BUCKETS`. Passing an
        explicit ladder overrides both — reload builds do this to match
        the live engine's shape."""
        with open(os.path.join(directory, "serving.json")) as fh:
            manifest = json.load(fh)
        if buckets is None:
            # lazy import: ladder.py's manifest helpers reach into
            # quant.variants, which sits above this module
            from gan_deeplearning4j_tpu.serving.ladder import manifest_ladder

            buckets = manifest_ladder(directory) or DEFAULT_BUCKETS
        if manifest.get("format_version", 0) > 1:
            raise ValueError(
                f"serving bundle format {manifest['format_version']} is newer "
                f"than supported"
            )

        def _path(key):
            name = manifest.get(key)
            return os.path.join(directory, name) if name else None

        return cls.from_checkpoints(
            generator=_path("generator"),
            classifier=_path("classifier"),
            buckets=buckets,
            feature_vertex=manifest.get("feature_vertex"),
            replicas=replicas,
            generation=manifest.get("generation"),
            export_gauge=export_gauge,
            staging_pool=staging_pool,
            precision=manifest.get("precision"),
            scenario=manifest.get("zoo"),
        )

    # -- introspection ------------------------------------------------------
    def export_generation(self) -> None:
        """Publish this engine's bundle generation to the process-wide
        ``serving_generation`` gauge — the moment an engine becomes THE
        served engine (construction by default; the reload plane defers it
        to the swap so a warming candidate never claims the gauge)."""
        self._g_generation.set(-1 if self.generation is None
                               else self.generation)

    @property
    def in_flight(self) -> int:
        """Dispatched-but-unfinalized flushes across every replica — the
        reload plane's retirement signal (an old engine is retired once
        its last flight drains to zero)."""
        with self._lock:
            return sum(self._outstanding)

    @property
    def kinds(self) -> Tuple[str, ...]:
        return tuple(self._kinds)

    def input_width(self, kind: str) -> int:
        return self._in_width[kind]

    # -- zoo scenario surface (docs/ZOO.md) ---------------------------------
    @property
    def conditional(self) -> bool:
        """Whether this bundle's manifest declares class conditioning —
        the gate for ``sample?class=k`` (service layer). Pre-zoo bundles
        (scenario None) are unconditional."""
        return bool(self.scenario) and self.scenario.get("conditioning") == "class"

    @property
    def class_count(self) -> int:
        """Number of condition classes (0 when unconditional)."""
        return int(self.scenario.get("num_classes", 0)) if self.conditional else 0

    def latent_width(self, kind: str) -> int:
        """The CALLER-facing latent width of a kind: the full input width
        minus the one-hot class block the service appends for conditional
        ``sample?class=k`` requests. Equals ``input_width`` for every
        unconditional kind — classify/features inputs are real rows, not
        latents, so only ``sample`` ever differs."""
        width = self._in_width[kind]
        if kind == "sample" and self.conditional:
            return width - self.class_count
        return width

    def scenario_manifest(self):
        """The parsed :class:`~gan_deeplearning4j_tpu.zoo.manifest.
        ScenarioManifest` (None for pre-zoo bundles). Lazy import — the
        engine stores the raw dict so serving has no hard zoo dependency."""
        if self.scenario is None:
            return None
        from gan_deeplearning4j_tpu.zoo.manifest import ScenarioManifest

        return ScenarioManifest.from_dict(self.scenario)

    @property
    def replica_count(self) -> int:
        return len(self._devices)

    @property
    def platform(self) -> str:
        """The device platform the ladder is compiled for ("cpu"/"tpu")."""
        return self._devices[0].platform

    def resident_param_bytes(self) -> int:
        """Device bytes ONE replica of this engine's parameters pins —
        the residency denominator the measured cost block records
        (quant/cost.py): bf16 params halve it, int8 weights quarter it.
        Staging buffers and executables are accounted separately (the
        shared pool's ``stats()`` and the compile ledger)."""
        import jax

        return sum(
            leaf.nbytes
            for replicas in self._params.values()
            for leaf in jax.tree_util.tree_leaves(replicas[0])
        )

    @property
    def default_pipeline_depth(self) -> int:
        """In-flight flush window the batcher uses unless overridden. On a
        real accelerator, two per replica: one executing plus one queued
        behind it so the device never waits on host assembly. On the CPU
        backend the "device" shares the host's cores — overlapping flushes
        just thrashes them — so one per replica."""
        per_replica = 1 if self._devices[0].platform == "cpu" else 2
        return per_replica * len(self._devices)

    @property
    def compile_counts(self) -> Dict[str, int]:
        """Distinct XLA compiles per kind so far (warmup + serve-time) —
        each must stay ≤ ``expected_max_compiles``."""
        with self._lock:
            return dict(self._compile_counts)

    @property
    def serve_compile_counts(self) -> Dict[str, int]:
        """Compiles that happened AFTER warmup completed — the fast-path
        contract is that this stays 0 per kind (every request rides a
        pre-compiled bucket executable)."""
        with self._lock:
            return dict(self._serve_compiles)

    @property
    def expected_max_compiles(self) -> int:
        """The bounded-compile invariant: per kind, at most one executable
        per (bucket, replica) plus one mesh bulk executable when more than
        one replica is routed."""
        r = len(self._devices)
        return len(self.buckets) * r + (1 if r > 1 else 0)

    @property
    def warming(self) -> bool:
        """True while a background warmup is still compiling the ladder."""
        t = self._warm_thread
        return t is not None and t.is_alive()

    @property
    def warmed(self) -> bool:
        return self._warmed

    @property
    def warm_failed(self) -> bool:
        """True when a warmup attempt raised — /healthz must surface this
        (the ladder is NOT compiled; lazy serve-time compiles would
        otherwise masquerade as a healthy replica)."""
        return self._warm_error is not None

    def stats(self) -> dict:
        """Engine-side observability merged into the service /metrics."""
        with self._lock:
            per_replica = [0] * len(self._devices)
            for (_, _, r) in self._compiled:
                per_replica[r] += 1
            return {
                "replicas": len(self._devices),
                "generation": self.generation,
                "precision": self.precision or "fp32",
                "replica_dispatches": list(self._dispatches),
                "replica_in_flight": list(self._outstanding),
                "compile_counts": dict(self._compile_counts),
                "serve_compile_counts": dict(self._serve_compiles),
                "padded_rows_wasted": dict(self._padded_waste),
                "buckets": list(self.buckets),
                "compiled_per_replica": per_replica,
                "warmup": "warm" if self._warmed else (
                    "warming" if self.warming else (
                        "failed" if self._warm_error is not None else "cold")),
            }

    # -- compilation --------------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _executable(self, kind: str, bucket: int, replica: int = 0):
        key = (kind, bucket, replica)
        exe = self._compiled.get(key)
        if exe is not None:
            return exe
        # compiles serialize on their OWN lock: ``self._lock`` guards only
        # cheap state (pool, routing, counters), so a multi-second XLA
        # compile — eager warmup working through the ladder — never stalls
        # requests whose executables are already cached
        with self._compile_lock:
            exe = self._compiled.get(key)
            if exe is not None:
                return exe
            import jax
            from jax.sharding import SingleDeviceSharding

            from gan_deeplearning4j_tpu.runtime.dtype import (
                compute_dtype_scope,
            )

            role, fn = self._kinds[kind]
            spec = jax.ShapeDtypeStruct(
                (bucket, self._in_width[kind]), np.float32,
                sharding=SingleDeviceSharding(self._devices[replica]),
            )
            # AOT: lower for the exact padded shape on the exact replica
            # device and keep the executable; serve-time calls can then
            # never re-trace or re-compile. The compute-dtype scope is
            # active during tracing only — a bf16 bundle's casts are
            # baked INTO the executable, not toggled per request (and
            # fp32 engines pin None so ambient state never leaks in).
            with TRACER.span("serve.engine.compile", kind=kind,
                             bucket=bucket, replica=replica), \
                    compute_dtype_scope(self._compute_dtype):
                exe = jax.jit(fn).lower(
                    self._params[role][replica], spec
                ).compile()
            with self._lock:
                self._compiled[key] = exe
                self._compile_counts[kind] += 1
                self._c_compiles[kind].inc()
                # a compile after warmup finished — OR after it failed —
                # is a serve-time compile: some request is paying for it
                if self._warmed or self._warm_error is not None:
                    self._serve_compiles[kind] += 1
                    self._c_serve_compiles[kind].inc()
            return exe

    def _bulk_executable(self, kind: str):
        """One mesh-sharded executable per kind that splits a
        ``top_bucket × replicas`` slab evenly across every replica — the
        bulk lane for oversized single-caller batches (offline scoring).
        Compiled at warmup only; returns None for single-replica engines."""
        if len(self._devices) < 2:
            return None
        exe = self._bulk.get(kind)
        if exe is not None:
            return exe
        with self._compile_lock:
            exe = self._bulk.get(kind)
            if exe is not None:
                return exe
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            from gan_deeplearning4j_tpu.runtime.dtype import (
                compute_dtype_scope,
            )

            mesh = Mesh(np.asarray(self._devices), ("replica",))
            replicated = NamedSharding(mesh, PartitionSpec())
            batched = NamedSharding(mesh, PartitionSpec("replica"))
            role, fn = self._kinds[kind]
            if role not in self._params_mesh:
                self._params_mesh[role] = jax.device_put(
                    self._params[role][0], replicated
                )
            self._batch_sharding = batched
            slab = len(self._devices) * self.buckets[-1]
            spec = jax.ShapeDtypeStruct(
                (slab, self._in_width[kind]), np.float32, sharding=batched
            )
            with TRACER.span("serve.engine.compile", kind=kind,
                             bucket=slab, replica="bulk"), \
                    compute_dtype_scope(self._compute_dtype):
                exe = jax.jit(fn).lower(
                    self._params_mesh[role], spec
                ).compile()
            with self._lock:
                self._bulk[kind] = exe
                self._compile_counts[kind] += 1
                self._c_compiles[kind].inc()
                if self._warmed or self._warm_error is not None:
                    self._serve_compiles[kind] += 1
                    self._c_serve_compiles[kind].inc()
            return exe

    def warmup(self, background: bool = False):
        """Compile the FULL ladder — every (kind, bucket, replica), plus
        the bulk lane when multi-replica — so no request ever pays a
        serve-time compile. ``background=True`` runs the compiles on a
        daemon thread (``warming`` is True until it finishes; ``/healthz``
        reports it); otherwise blocks and returns per-kind compile counts."""
        if background:
            with self._lock:
                if self._warm_thread is not None and self._warm_thread.is_alive():
                    return self._warm_thread
                t = threading.Thread(
                    target=self._warm_all_quiet, name="engine-warmup",
                    daemon=True,
                )
                self._warm_thread = t
            t.start()
            return t
        self._warm_all()
        return self.compile_counts

    def _warm_all_quiet(self) -> None:
        """Background-thread wrapper: the failure is STORED (surfaced via
        ``wait_warm``/``warm_failed``/healthz), not re-raised into an
        unhandled-thread-exception hook."""
        try:
            self._warm_all()
        except BaseException:
            pass

    def _warm_all(self) -> None:
        try:
            for kind in self._kinds:
                for r in range(len(self._devices)):
                    for b in self.buckets:
                        self._executable(kind, b, r)
                self._bulk_executable(kind)
            self._warm_error = None
        except BaseException as exc:  # surfaced by wait_warm/healthz
            self._warm_error = exc
            raise
        finally:
            self._warmed = self._warm_error is None

    def wait_warm(self, timeout: Optional[float] = None) -> bool:
        """Block until a background warmup finishes. True when the engine
        is warm; raises the warmup's error if compiling failed."""
        t = self._warm_thread
        if t is not None:
            t.join(timeout)
        if self._warm_error is not None:
            raise RuntimeError("engine warmup failed") from self._warm_error
        return self._warmed

    # -- staging pool -------------------------------------------------------
    def _checkout(self, kind: str, bucket: int) -> _StagingBuf:
        if self._shared_staging is not None:
            return self._shared_staging.checkout(
                bucket, self._in_width[kind])
        key = (kind, bucket)
        with self._lock:
            pool = self._staging.get(key)
            if pool:
                return pool.pop()
        return _StagingBuf(bucket, self._in_width[kind])

    def _checkin(self, kind: str, buf: _StagingBuf) -> None:
        if self._shared_staging is not None:
            self._shared_staging.checkin(buf)
            return
        key = (kind, buf.arr.shape[0])
        with self._lock:
            pool = self._staging.setdefault(key, [])
            if len(pool) < _POOL_LIMIT:
                pool.append(buf)

    def _pick_replica(self) -> int:
        with self._lock:
            load = min(self._outstanding)
            candidates = [i for i, o in enumerate(self._outstanding)
                          if o == load]
            r = candidates[self._rr % len(candidates)]
            self._rr += 1
            self._outstanding[r] += 1
            self._dispatches[r] += 1
        self._c_dispatches[r].inc()
        return r

    # -- execution ----------------------------------------------------------
    def _validate(self, kind: str, rows_list) -> int:
        if kind not in self._kinds:
            raise KeyError(
                f"unknown request kind {kind!r}; serving {sorted(self._kinds)}"
            )
        width = self._in_width[kind]
        total = 0
        for rows in rows_list:
            if (rows.ndim != 2 or rows.shape[0] < 1
                    or rows.shape[1] != width):
                raise ValueError(
                    f"{kind}: expected (n >= 1, {width}) rows, "
                    f"got {rows.shape}"
                )
            total += rows.shape[0]
        if not rows_list:
            raise ValueError(f"{kind}: empty batch")
        return total

    def dispatch(self, kind: str, rows_list: Sequence[np.ndarray]) -> _Flight:
        """Assemble the riders into bucket-shaped staged buffers and launch
        the AOT executables WITHOUT waiting for results (async dispatch —
        the caller overlaps host work with device execution and collects
        via :meth:`finalize`). Rider arrays are copied once each, directly
        into the pinned staging buffer — no intermediate concat."""
        rows_list = [np.asarray(r, dtype=np.float32) for r in rows_list]
        total = self._validate(kind, rows_list)
        top = self.buckets[-1]
        role, _ = self._kinds[kind]

        parts = []
        try:
            return self._dispatch_chunks(
                kind, role, rows_list, total, top, parts)
        except BaseException:
            # a failed later chunk must release EVERY earlier chunk's
            # buffer + replica reservation, or routing counts phantom load
            for _, _, buf, r in parts:
                self._release(kind, buf, r)
            raise

    def _dispatch_chunks(self, kind, role, rows_list, total, top,
                         parts) -> "_Flight":
        import jax

        # rider cursor: (index into rows_list, row offset within that rider)
        ri, roff = 0, 0
        remaining = total
        while remaining > 0:
            # bulk lane: a full replicas×top slab from ONE rider splits
            # across every replica in a single mesh-sharded call
            slab = len(self._devices) * top
            if (remaining >= slab and len(self._devices) > 1
                    and roff + slab <= rows_list[ri].shape[0]):
                exe = self._bulk_executable(kind)
                if exe is not None:
                    chunk = rows_list[ri][roff:roff + slab]
                    dev = jax.device_put(chunk, self._batch_sharding)
                    # _params_mesh is published before _bulk_executable
                    # returns non-None (both written under _compile_lock),
                    # so this lockless hot-path read never sees a partial
                    # value; taking _compile_lock here would park dispatch
                    # behind multi-second XLA compiles
                    parts.append((exe(self._params_mesh[role], dev),  # jaxlint: disable=JG024 (publish-ordered behind _bulk_executable)
                                  slab, None, None))
                    roff += slab
                    remaining -= slab
                    if roff == rows_list[ri].shape[0]:
                        ri, roff = ri + 1, 0
                    continue
            n = min(top, remaining)
            bucket = self._bucket_for(n)
            waste = bucket - n
            if waste:
                with self._lock:
                    self._padded_waste[kind] += waste
                self._c_waste[kind].inc(waste)
            buf = self._checkout(kind, bucket)
            filled = 0
            while filled < n:
                rider = rows_list[ri]
                take = min(n - filled, rider.shape[0] - roff)
                buf.arr[filled:filled + take] = rider[roff:roff + take]
                filled += take
                roff += take
                if roff == rider.shape[0]:
                    ri, roff = ri + 1, 0
            buf.reset_tail(n)
            r = self._pick_replica()
            try:
                dev = jax.device_put(buf.arr, self._devices[r])
                out = self._executable(kind, bucket, r)(
                    self._params[role][r], dev
                )
            except BaseException:
                # undo the reservation or least-loaded routing (and
                # /metrics in-flight) would count phantom load forever
                self._release(kind, buf, r)
                raise
            parts.append((out, n, buf, r))
            remaining -= n
        # the flight's completion lane is the replica its FIRST replica-
        # routed chunk ran on (bulk-lane parts carry no replica); a
        # bulk-only flight rides lane 0
        lane = next((r for _, _, _, r in parts if r is not None), 0)
        return _Flight(kind, total, parts, lane=lane)

    def _release(self, kind: str, buf: Optional[_StagingBuf],
                 r: Optional[int]) -> None:
        if buf is not None:
            self._checkin(kind, buf)
        if r is not None:
            with self._lock:
                self._outstanding[r] -= 1

    def finalize(self, flight: _Flight) -> np.ndarray:
        """Block until the flight's device work is done, slice the padding
        off, recycle the staging buffers, and return the result rows.
        Buffers and replica in-flight counts are released for EVERY part,
        even when a device sync raises partway through."""
        outs = []
        parts = list(flight.parts)
        flight.parts = []  # release exactly once, even if called twice
        try:
            for out, n, buf, r in parts:
                outs.append(np.asarray(out)[:n])  # device sync + transfer
        finally:
            for _, _, buf, r in parts:
                self._release(flight.kind, buf, r)
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    def run(self, kind: str, rows: np.ndarray) -> np.ndarray:
        """Execute one batch synchronously: staged assembly, AOT execute,
        unpad. Batches larger than the top bucket are served in top-bucket
        chunks (and, multi-replica, full slabs ride the bulk lane)."""
        rows = np.asarray(rows, dtype=np.float32)
        return self.finalize(self.dispatch(kind, [rows]))

    def run_host(self, kind: str, rows: np.ndarray) -> np.ndarray:
        """Reference host-assembly path (the PR 3 semantics): pad with a
        fresh ``np.zeros`` + ``np.concatenate`` per chunk and execute on
        replica 0. Kept as the bit-exactness oracle for the staged path
        (tests) and the ``--legacy`` mode of ``scripts/serve_bench.py``."""
        rows = np.asarray(rows, dtype=np.float32)
        self._validate(kind, [rows])
        role, _ = self._kinds[kind]
        params = self._params[role][0]
        top = self.buckets[-1]
        outs = []
        for start in range(0, rows.shape[0], top):
            chunk = rows[start:start + top]
            bucket = self._bucket_for(chunk.shape[0])
            if chunk.shape[0] < bucket:
                pad = np.zeros(
                    (bucket - chunk.shape[0], chunk.shape[1]), np.float32
                )
                chunk = np.concatenate([chunk, pad])
            out = self._executable(kind, bucket, 0)(params, chunk)
            outs.append(
                np.asarray(out)[: min(top, rows.shape[0] - start)]
            )
        return outs[0] if len(outs) == 1 else np.concatenate(outs)
