"""ServingEngine — checkpoint-backed executor with a padded bucket ladder.

Loads serializer checkpoints (``utils/serializer.read_model`` — topology +
params, no training code needed), pins the weights on device ONCE, and
pre-compiles one XLA executable per (request kind, batch bucket) via jit's
AOT path (``lower().compile()``). Requests are padded up to the smallest
bucket and sliced back, so an arbitrary request size NEVER triggers a fresh
compile at serve time — with free-running shapes every new batch size would
stall a request tail for seconds of XLA compilation (the recompilation
hazard jaxlint JG004 polices in training code, recurring here as a serving
tail-latency cliff). Compiles are counted per kind; the serve bench asserts
the count stays ≤ the ladder size.

Request kinds (SURVEY §0 — the trained artifacts, not the loop):

- ``sample``:   z (n, z_size)        -> generator images (n, num_features)
- ``classify``: x (n, num_features)  -> class probabilities (n, num_classes)
- ``features``: x (n, num_features)  -> discriminator-feature activations
  at the transfer classifier's feature vertex (mnist: ``dis_dense_layer_6``)
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

DEFAULT_BUCKETS = (1, 8, 32, 128)


class ServingEngine:
    """Model-backed executor: ``run(kind, rows) -> rows``.

    ``models`` maps role ("generator"/"classifier") to a loaded
    ``(ComputationGraph, params)`` pair. Thread-safe: AOT executables are
    compiled under a lock (the batcher worker is single-threaded, but the
    in-process API may be driven from many threads)."""

    def __init__(
        self,
        models: Dict[str, Tuple[object, dict]],
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        feature_vertex: Optional[str] = None,
    ):
        import jax

        if not models:
            raise ValueError("ServingEngine needs at least one model")
        buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"invalid bucket ladder {buckets!r}")
        self.buckets = buckets
        self.feature_vertex = feature_vertex
        # weights cross to the device once, here — never per request
        self._graphs = {role: graph for role, (graph, _) in models.items()}
        self._params = {
            role: jax.device_put(params) for role, (_, params) in models.items()
        }

        self._kinds: Dict[str, Tuple[str, object]] = {}  # kind -> (role, fn)
        if "generator" in models:
            gen = self._graphs["generator"]
            # flatten NHWC image outputs to (n, features): the wire contract
            # is rows, matching the reference's flat CSV exports
            self._kinds["sample"] = (
                "generator",
                lambda p, z: gen.output(p, z, train=False).reshape(
                    (z.shape[0], -1)
                ),
            )
        if "classifier" in models:
            cv = self._graphs["classifier"]
            self._kinds["classify"] = (
                "classifier",
                lambda p, x: cv.output(p, x, train=False),
            )
            if feature_vertex is not None:
                if feature_vertex not in {v.name for v in cv.vertices}:
                    raise ValueError(
                        f"feature vertex {feature_vertex!r} is not a vertex of "
                        f"the classifier graph"
                    )
                self._kinds["features"] = (
                    "classifier",
                    lambda p, x: cv.feed_forward(p, x, train=False)[feature_vertex],
                )

        self._in_width = {
            kind: self._graphs[role].input_types[0].features
            for kind, (role, _) in self._kinds.items()
        }
        self._compiled: Dict[Tuple[str, int], object] = {}
        self._compile_counts: Dict[str, int] = {k: 0 for k in self._kinds}
        self._lock = threading.Lock()

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_checkpoints(
        cls,
        generator: Optional[str] = None,
        classifier: Optional[str] = None,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        feature_vertex: Optional[str] = None,
    ) -> "ServingEngine":
        """Restore from serializer checkpoint zips. Updater state is never
        loaded — a serving replica has no optimizer."""
        from gan_deeplearning4j_tpu.utils.serializer import read_model

        models = {}
        for role, path in (("generator", generator), ("classifier", classifier)):
            if path is None:
                continue
            graph, params, _, _ = read_model(path, load_updater=False)
            models[role] = (graph, params)
        return cls(models, buckets=buckets, feature_vertex=feature_vertex)

    @classmethod
    def from_bundle(
        cls, directory: str, *, buckets: Sequence[int] = DEFAULT_BUCKETS
    ) -> "ServingEngine":
        """Load a ``serving.json`` bundle published by
        ``GanExperiment.publish_for_serving``."""
        with open(os.path.join(directory, "serving.json")) as fh:
            manifest = json.load(fh)
        if manifest.get("format_version", 0) > 1:
            raise ValueError(
                f"serving bundle format {manifest['format_version']} is newer "
                f"than supported"
            )

        def _path(key):
            name = manifest.get(key)
            return os.path.join(directory, name) if name else None

        return cls.from_checkpoints(
            generator=_path("generator"),
            classifier=_path("classifier"),
            buckets=buckets,
            feature_vertex=manifest.get("feature_vertex"),
        )

    # -- introspection ------------------------------------------------------
    @property
    def kinds(self) -> Tuple[str, ...]:
        return tuple(self._kinds)

    def input_width(self, kind: str) -> int:
        return self._in_width[kind]

    @property
    def compile_counts(self) -> Dict[str, int]:
        """Distinct XLA compiles per kind so far — the bench's ladder
        invariant (each must stay ≤ ``len(self.buckets)``)."""
        with self._lock:
            return dict(self._compile_counts)

    # -- compilation --------------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _executable(self, kind: str, bucket: int):
        key = (kind, bucket)
        exe = self._compiled.get(key)
        if exe is not None:
            return exe
        with self._lock:
            exe = self._compiled.get(key)
            if exe is not None:
                return exe
            import jax

            role, fn = self._kinds[kind]
            spec = jax.ShapeDtypeStruct(
                (bucket, self._in_width[kind]), np.float32
            )
            # AOT: lower for the exact padded shape and keep the executable;
            # serve-time calls can then never re-trace or re-compile
            exe = jax.jit(fn).lower(self._params[role], spec).compile()
            self._compiled[key] = exe
            self._compile_counts[kind] += 1
            return exe

    def warmup(self) -> Dict[str, int]:
        """Compile the FULL ladder up front (cold-start cost paid before the
        first request, not by it). Returns the per-kind compile counts."""
        for kind in self._kinds:
            for b in self.buckets:
                self._executable(kind, b)
        return self.compile_counts

    # -- execution ----------------------------------------------------------
    def run(self, kind: str, rows: np.ndarray) -> np.ndarray:
        """Execute one batch: pad to the bucket, run the AOT executable,
        slice the padding back off. Batches larger than the top bucket are
        served in top-bucket chunks (the batcher's max_batch normally
        prevents that, but the engine stays correct standalone)."""
        if kind not in self._kinds:
            raise KeyError(
                f"unknown request kind {kind!r}; serving {sorted(self._kinds)}"
            )
        rows = np.asarray(rows, dtype=np.float32)
        if (rows.ndim != 2 or rows.shape[0] < 1
                or rows.shape[1] != self._in_width[kind]):
            raise ValueError(
                f"{kind}: expected (n >= 1, {self._in_width[kind]}) rows, "
                f"got {rows.shape}"
            )
        role, _ = self._kinds[kind]
        params = self._params[role]
        top = self.buckets[-1]
        outs = []
        for start in range(0, rows.shape[0], top):
            chunk = rows[start:start + top]
            bucket = self._bucket_for(chunk.shape[0])
            if chunk.shape[0] < bucket:
                pad = np.zeros(
                    (bucket - chunk.shape[0], chunk.shape[1]), np.float32
                )
                chunk = np.concatenate([chunk, pad])
            out = self._executable(kind, bucket)(params, chunk)
            outs.append(
                np.asarray(out)[: min(top, rows.shape[0] - start)]
            )
        return outs[0] if len(outs) == 1 else np.concatenate(outs)
