"""InferenceService — the request surface over engine + micro-batcher.

Two front ends share one code path (``handle``): the in-process Python API
(what tests and the bench drive — no sockets, same batching semantics) and
a stdlib-only HTTP JSON endpoint (``http.server.ThreadingHTTPServer`` — no
framework dependency, per the repo's no-new-deps rule). Endpoints:

- ``POST /v1/sample``    {"data": [[z...], ...]}  -> {"status","data"}
- ``POST /v1/classify``  {"data": [[x...], ...]}  -> {"status","data"}
- ``POST /v1/features``  {"data": [[x...], ...]}  -> {"status","data"}
- ``GET  /healthz``      liveness + loaded kinds + served bundle generation
- ``GET  /metrics``      request counters, p50/p95/p99 latency, batch-
  occupancy histogram, shed counts, per-kind compile counts, generation;
  ``?format=prom`` switches to Prometheus text exposition straight off the
  process-wide telemetry registry (docs/OBSERVABILITY.md);
  ``?scope=registry`` returns the raw registry snapshot with histogram
  samples — the fleet router's aggregation feed (``telemetry/aggregate``)
- ``X-Trace-Id`` on ``POST`` requests propagates a correlation id: the
  handler adopts a valid header value instead of minting, so this
  worker's spans join the fleet router's (or any upstream's) causal
  chain in a merged trace
- ``POST /debug/trace?ms=N``  on-demand ``jax.profiler`` device capture
  into the service's artifacts dir — 202 + the artifact path (async;
  ``block=1`` waits for 200), 409 while one is running
- ``POST /admin/reload``  force an immediate reload-plane poll (202;
  ``block=1`` waits for the cycle and answers 200; 409 while a reload is
  in progress or when no reload plane is attached — docs/DEPLOY.md)
- ``POST /admin/drain``   mark this worker draining (``off=1`` clears):
  ``/healthz`` reports ``"draining"`` so a fleet router stops routing to
  it and health probes do not re-admit it, while requests already in the
  pipeline still finalize normally — the draining-restart handshake
  (docs/FLEET.md); the worker itself sheds nothing
- ``GET  /debug/spans``  the span tracer's Chrome trace JSON (Perfetto-
  loadable; empty unless tracing is enabled)

Shed responses map to HTTP 503 (overloaded / deadline) so load balancers
can react; engine errors map to 500, bad requests to 400.
"""

from __future__ import annotations

import json
import logging
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs

import numpy as np

from gan_deeplearning4j_tpu.serving.batcher import MicroBatcher, ServeResult
from gan_deeplearning4j_tpu.serving.engine import ServingEngine
from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import (
    TRACER,
    bind_trace_id,
    new_trace_id,
    sanitize_trace_id,
    unbind_trace_id,
)

logger = logging.getLogger(__name__)

_STATUS_HTTP = {"ok": 200, "overloaded": 503, "deadline": 503, "error": 500}


class InferenceService:
    """The in-process serving API. One micro-batcher fronts the engine;
    every public call goes through it, so in-process and HTTP callers share
    batching, deadlines, backpressure, and the dispatch/finalize pipeline.

    ``warmup`` controls when the engine compiles its executable ladder:
    ``True``/``"sync"`` blocks construction until warm (no request can
    ever see a compile); ``"eager"`` compiles on a background thread —
    the service accepts requests immediately and ``/healthz`` reports
    ``"warming"`` until the ladder is done; ``False`` leaves compiles
    lazy (first request per bucket pays one — only for tests/tools)."""

    def __init__(
        self,
        engine: ServingEngine,
        *,
        max_batch: Optional[int] = None,
        max_latency: float = 0.005,
        max_queue: int = 256,
        default_timeout: float = 5.0,
        warmup="sync",
        pipeline_depth: Optional[int] = None,
        artifacts_dir: Optional[str] = None,
    ):
        # where POST /debug/trace dumps device captures (resolved lazily so
        # constructing a service never touches the filesystem)
        self.artifacts_dir = artifacts_dir
        # the reload control plane (deploy.ReloadController), when attached:
        # owns POST /admin/reload and the /healthz "reload" block
        self.reloader = None
        # draining flag (POST /admin/drain): advisory — the worker keeps
        # answering, but /healthz stops reporting "ok" so a fleet router
        # neither routes to it nor re-admits it while its pipeline empties
        self.draining = False
        if warmup in (True, "sync"):
            engine.warmup()
        elif warmup in ("eager", "background"):
            engine.warmup(background=True)
        elif warmup not in (False, None, "off"):
            raise ValueError(f"unknown warmup mode {warmup!r}")
        self.batcher = MicroBatcher(
            engine=engine,
            max_batch=max_batch or engine.buckets[-1],
            max_latency=max_latency,
            max_queue=max_queue,
            default_timeout=default_timeout,
            pipeline_depth=pipeline_depth,
        )

    @property
    def engine(self) -> ServingEngine:
        """The engine CURRENTLY serving — resolved through the batcher's
        lock-guarded swap seam, so after a zero-downtime reload every
        surface (healthz, metrics, routing) reflects the new engine."""
        return self.batcher.engine

    def attach_reloader(self, controller) -> None:
        """Wire a ``deploy.ReloadController``: enables POST /admin/reload
        and the /healthz candidate-state block."""
        self.reloader = controller

    # -- typed convenience wrappers ----------------------------------------
    def sample(self, z, timeout: Optional[float] = None) -> ServeResult:
        return self.batcher.submit("sample", z, timeout=timeout)

    def classify(self, x, timeout: Optional[float] = None) -> ServeResult:
        return self.batcher.submit("classify", x, timeout=timeout)

    def features(self, x, timeout: Optional[float] = None) -> ServeResult:
        return self.batcher.submit("features", x, timeout=timeout)

    # -- shared request handler --------------------------------------------
    def healthz(self) -> dict:
        engine = self.engine  # one snapshot — a swap mid-handler is benign
        if engine.warm_failed:
            # a failed background warmup must NOT look healthy: the ladder
            # is not compiled, so requests would pay serve-time compiles
            status = "error"
        elif self.draining:
            # draining outranks warming/ok: a router must neither route to
            # nor re-admit a worker that is being rotated out
            status = "draining"
        elif engine.warming:
            status = "warming"
        else:
            status = "ok"
        body = {
            "status": status,
            "kinds": list(engine.kinds),
            "buckets": list(engine.buckets),
            "replicas": engine.replica_count,
            # the version the reload plane (and any canary gate) keys on:
            # None when the engine was loaded from bare checkpoints
            "generation": engine.generation,
        }
        if engine.scenario is not None:
            # the bundle's zoo identity (docs/ZOO.md) — lets an operator
            # (and the zoo drill) see which scenario serves without
            # reading the bundle manifest off disk
            body["scenario"] = dict(engine.scenario)
        if self.reloader is not None:
            # candidate state (idle/warming/canary/swapping/rejected), swap
            # and rejection counts — the reload plane's liveness surface
            body["reload"] = self.reloader.status()
        if status == "error":
            body["error"] = "engine warmup failed"
        return body

    def metrics(self) -> dict:
        """The JSON ``/metrics`` payload — the PR 3 schema plus
        ``generation`` (a schema-compatible superset; every number now
        originates in the telemetry registry or the batcher ledger)."""
        engine = self.engine  # one snapshot across the payload
        return {
            **self.batcher.metrics(),
            "generation": engine.generation,
            "draining": self.draining,
            "engine": engine.stats(),
            "compile_counts": engine.compile_counts,
        }

    def metrics_text(self) -> str:
        """Prometheus text exposition of the process-wide registry —
        ``GET /metrics?format=prom``."""
        return get_registry().to_prometheus()

    def _debug_trace(self, params: dict) -> Tuple[int, dict]:
        """POST /debug/trace?ms=N — one bounded device capture, dumped
        under the artifacts dir. Asynchronous by default (202 + the path
        the artifact will land at): profiler start/stop costs tens of
        seconds on a cold profiler, far past any sane client timeout, and
        the capture wants to see live traffic anyway. ``block=1`` waits
        and answers 200 once the artifact is on disk."""
        from gan_deeplearning4j_tpu.telemetry import device as _device

        try:
            ms = int(params.get("ms", ["1000"])[0])
            if ms < 1 or ms > 60_000:
                raise ValueError(ms)
        except (TypeError, ValueError):
            return 400, {"status": "error",
                         "error": f"bad 'ms': {params.get('ms')!r} "
                                  f"(want 1..60000)"}
        block = params.get("block", ["0"])[0] not in ("0", "", "false")
        artifacts = self.artifacts_dir or _device.default_artifacts_dir()
        try:
            if block:
                path = _device.capture_device_trace(artifacts, duration_ms=ms)
                return 200, {"status": "ok", "artifact": path,
                             "duration_ms": ms}
            _, path = _device.capture_async(artifacts, duration_ms=ms)
        except _device.CaptureBusy as exc:
            return 409, {"status": "error", "error": str(exc)}
        return 202, {"status": "accepted", "artifact": path,
                     "duration_ms": ms}

    def _admin_reload(self, params: dict) -> Tuple[int, dict]:
        """POST /admin/reload — force an immediate reload-plane poll,
        skipping the remainder of the watcher interval. Semantics mirror
        ``/debug/trace``: async by default (202 + current reload state —
        a candidate warm/canary cycle can take seconds), ``block=1`` waits
        for the triggered cycle and answers 200 with its outcome, 409 when
        a reload cycle is already in progress (or when no reload plane is
        attached — there is nothing to poll)."""
        if self.reloader is None:
            return 409, {"status": "error",
                         "error": "no reload plane attached (start the "
                                  "server with --reload-store)"}
        from gan_deeplearning4j_tpu.deploy.reloader import ReloadBusy

        block = params.get("block", ["0"])[0] not in ("0", "", "false")
        try:
            status = self.reloader.poll_now(wait=block)
        except ReloadBusy as exc:
            return 409, {"status": "error", "error": str(exc)}
        if block:
            return 200, {"status": "ok", "reload": status}
        return 202, {"status": "accepted", "reload": status}

    def handle(self, method: str, path: str, payload: Optional[dict] = None,
               trace_id: Optional[str] = None) -> Tuple[int, dict]:
        """(http_status, response_body) for one request — the single routing
        table both front ends use. (``/metrics?format=prom`` is the one
        route with a non-JSON body; the HTTP front end serves it from
        :meth:`metrics_text` before reaching this table.)

        ``trace_id`` is a propagated correlation id (the fleet router's —
        or any client's — ``X-Trace-Id`` header): when valid it is adopted
        as this request's correlation id instead of minting one, so this
        worker's spans join the caller's causal chain in a merged trace."""
        path, _, query = path.partition("?")
        params = parse_qs(query) if query else {}
        if method == "GET" and path == "/healthz":
            return 200, self.healthz()
        if method == "GET" and path == "/metrics":
            if params.get("scope", [""])[0] == "registry":
                # the fleet aggregation feed: the full process registry
                # WITH histogram samples, so the router's merge can keep
                # the nearest-rank percentile contract fleet-wide
                # (telemetry/aggregate.py)
                return 200, get_registry().snapshot(include_samples=True)
            return 200, self.metrics()
        if method == "GET" and path == "/debug/spans":
            return 200, TRACER.chrome_trace(
                {"source": "gan_deeplearning4j_tpu.serving"})
        if method == "POST" and path == "/debug/trace":
            return self._debug_trace(params)
        if method == "POST" and path == "/admin/reload":
            return self._admin_reload(params)
        if method == "POST" and path == "/admin/drain":
            # the fleet manager's draining-restart handshake: mark (or with
            # off=1 clear) drain, answer the resulting health state — the
            # caller then watches /metrics until the pipeline empties
            self.draining = params.get("off", ["0"])[0] in ("0", "", "false")
            return 200, {"status": "ok", "draining": self.draining}
        if method == "POST" and path.startswith("/v1/"):
            kind = path[len("/v1/"):]
            # one engine snapshot for the whole request: a swap between the
            # kinds check and the width check must not mix two engines
            engine = self.engine
            if kind not in engine.kinds:
                return 404, {"status": "error",
                             "error": f"unknown request kind {kind!r}"}
            data = (payload or {}).get("data")
            if data is None:
                return 400, {"status": "error", "error": "missing 'data'"}
            try:
                rows = np.asarray(data, dtype=np.float32)
            except (TypeError, ValueError) as exc:
                return 400, {"status": "error", "error": f"bad 'data': {exc}"}
            if rows.ndim == 1:
                rows = rows[None, :]
            # conditional sampling (docs/ZOO.md): ``/v1/sample?class=k``
            # takes BASE-z rows and appends the one-hot class embedding
            # here, so the widened rows flow through the existing width
            # check, batcher, and AOT bucket ladder untouched — zero new
            # compile surface. Unconditional bundles 400 the parameter.
            cls_param = params.get("class", [None])[0]
            if cls_param is not None:
                if kind != "sample":
                    return 400, {"status": "error",
                                 "error": f"?class= applies to the sample "
                                          f"kind, not {kind!r}"}
                if not engine.conditional:
                    return 400, {"status": "error",
                                 "error": "this bundle is unconditional — "
                                          "its manifest declares no class "
                                          "conditioning"}
                try:
                    label = int(cls_param)
                except ValueError:
                    return 400, {"status": "error",
                                 "error": f"bad 'class': {cls_param!r}"}
                if not 0 <= label < engine.class_count:
                    return 400, {
                        "status": "error",
                        "error": f"class {label} out of range "
                                 f"[0, {engine.class_count})",
                    }
                latent = engine.latent_width(kind)
                if rows.ndim != 2 or rows.shape[0] < 1 or rows.shape[1] != latent:
                    return 400, {
                        "status": "error",
                        "error": f"{kind}?class={label}: expected "
                                 f"(n >= 1, {latent}) latent rows, "
                                 f"got {tuple(rows.shape)}",
                    }
                onehot = np.zeros(
                    (rows.shape[0], engine.class_count), dtype=np.float32)
                onehot[:, label] = 1.0
                rows = np.concatenate([rows, onehot], axis=1)
            elif kind == "sample" and engine.conditional:
                # a conditional bundle still serves UNCONDITIONAL full-width
                # rows (caller supplies its own embedding) — the drills'
                # parity oracle and the mux plane's model-pinned probes rely
                # on this — but a bare latent-width row without ?class= is
                # a caller error worth a precise message
                if rows.ndim == 2 and rows.shape[1] == engine.latent_width(kind):
                    return 400, {
                        "status": "error",
                        "error": f"sample: got {rows.shape[1]}-wide latent "
                                 f"rows without ?class=k — pass ?class= or "
                                 f"supply full {engine.input_width(kind)}-"
                                 f"wide rows with the embedding",
                    }
            width = engine.input_width(kind)
            # reject malformed shapes HERE: a bad row must 400 its own
            # request, never reach the shared batch and error its riders
            if rows.ndim != 2 or rows.shape[0] < 1 or rows.shape[1] != width:
                return 400, {
                    "status": "error",
                    "error": f"{kind}: expected (n >= 1, {width}) rows, "
                             f"got {tuple(rows.shape)}",
                }
            timeout = (payload or {}).get("timeout")
            if timeout is not None:
                try:
                    timeout = float(timeout)
                except (TypeError, ValueError):
                    return 400, {"status": "error",
                                 "error": f"bad 'timeout': {timeout!r}"}
            if TRACER.enabled:
                # one correlation id per request: the batcher's submit
                # picks it off the contextvar and carries it across the
                # pipeline's threads. A propagated id (the router's
                # X-Trace-Id) is adopted so retried attempts on two
                # workers share one causal chain; otherwise mint
                token = bind_trace_id(
                    sanitize_trace_id(trace_id) or new_trace_id())
                try:
                    with TRACER.span("serve.request", kind=kind,
                                     rows=int(rows.shape[0])):
                        result = self.batcher.submit(
                            kind, rows, timeout=timeout)
                finally:
                    unbind_trace_id(token)
            else:
                result = self.batcher.submit(kind, rows, timeout=timeout)
            body = {"status": result.status,
                    "latency_ms": result.latency_s * 1e3}
            if result.ok:
                body["data"] = np.asarray(result.data).tolist()
            elif result.error:
                body["error"] = result.error
            return _STATUS_HTTP.get(result.status, 500), body
        return 404, {"status": "error", "error": f"no route {method} {path}"}

    def close(self) -> None:
        self.batcher.close()


# -- HTTP front end ---------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    service: InferenceService = None  # bound by make_server

    def _respond(self, status: int, body: dict) -> None:
        data = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (http.server naming contract)
        try:
            route, _, query = self.path.partition("?")
            if (route == "/metrics"
                    and "prom" in parse_qs(query).get("format", [])):
                # the one non-JSON body: Prometheus text exposition
                data = self.service.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            status, body = self.service.handle("GET", self.path)
        except Exception as exc:  # a handler bug must answer 500, not reset
            logger.exception("GET %s failed", self.path)
            status, body = 500, {"status": "error",
                                 "error": f"{type(exc).__name__}: {exc}"}
        self._respond(status, body)

    def do_POST(self):  # noqa: N802
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, json.JSONDecodeError) as exc:
            self._respond(400, {"status": "error", "error": f"bad JSON: {exc}"})
            return
        try:
            # the propagation header (docs/OBSERVABILITY.md): adopt the
            # router's/client's correlation id into this request's spans
            status, body = self.service.handle(
                "POST", self.path, payload,
                trace_id=self.headers.get("X-Trace-Id"))
        except Exception as exc:
            logger.exception("POST %s failed", self.path)
            status, body = 500, {"status": "error",
                                 "error": f"{type(exc).__name__}: {exc}"}
        self._respond(status, body)

    def log_message(self, fmt, *args):  # route to logging, not stderr
        logger.debug("%s - %s", self.address_string(), fmt % args)


def make_server(service: InferenceService, host: str = "127.0.0.1",
                port: int = 8000) -> ThreadingHTTPServer:
    """Bind (but do not start) the HTTP front end; ``port=0`` picks a free
    port (tests). Call ``serve_forever()`` or drive it from a thread."""
    handler = type("BoundHandler", (_Handler,), {"service": service})
    return ThreadingHTTPServer((host, port), handler)


def serve_forever(service: InferenceService, host: str, port: int) -> None:
    server = make_server(service, host, port)
    logger.info("serving on http://%s:%d (kinds: %s)", host,
                server.server_address[1], ",".join(service.engine.kinds))
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        service.close()
