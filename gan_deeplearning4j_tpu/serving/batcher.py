"""Dynamic micro-batcher — the serving throughput lever (μ-cuDNN-style).

Single-request dispatch wastes an accelerator: a batch-1 forward pays the
same dispatch latency as batch-128 for ~1% of the useful work. This module
coalesces concurrent requests of the same kind into one device batch under
two triggers — a full batch (``max_batch`` rows) or the oldest request
aging past ``max_latency`` — the classic throughput/latency trade of
server-side batching (*TensorFlow: a system for large-scale ML*, §4.3).

Execution is a TWO-STAGE PIPELINE (the continuous-batching shape of the
serving literature — Orca-style iteration-level scheduling in PAPERS.md):
a worker thread cuts a batch and *dispatches* it (host staging + async
device launch via ``engine.dispatch``), and completer threads *finalize*
it (block on the device, scatter rows back to callers). Because XLA
dispatch is asynchronous, host assembly of batch N+1 overlaps device
execution of batch N. The in-flight window is bounded
(``pipeline_depth``): the worker will not cut a new batch while the window
is full, so requests keep queueing — which deepens coalescing exactly when
the device is the bottleneck — and device work is never launched for more
flushes than the window allows. With ``pipeline_depth=1`` the pipeline
degenerates to strictly serial flushes (the pre-pipeline behavior); that
is the default for plain ``run_fn`` engines, which have no async seam.

Completion runs in PER-REPLICA LANES: a multi-replica engine gets one
completer thread per replica, and every dispatched flush lands in the lane
of the replica it was routed to (``handle.lane``, stamped by the engine's
dispatch). Finalize order is preserved *within* a lane — the device
executes a replica's flushes in dispatch order, so lane order is the only
order that matters — but one replica's slow finalize no longer
head-of-line blocks another replica's already-finished flush behind it in
a global queue. A handle without a lane (run_fn mode, fakes) rides lane 0,
which with a single-replica engine reproduces the old single-completer
behavior exactly.

Backpressure is explicit, not emergent: the queue is bounded, and a submit
against a full queue returns an ``overloaded`` result IMMEDIATELY instead
of blocking or growing the queue without bound — under overload a serving
tier must shed load in O(1), because every queued request it cannot serve
within its deadline is work thrown away *after* paying for it. Requests
that expire while queued are likewise shed with ``deadline`` before any
device work is spent on them.

The batching policy itself stays engine-agnostic: pass ``run_fn`` for any
synchronous ``(kind, rows) -> rows`` callable (unit tests use fakes), or
``engine=`` for an object with the async ``dispatch(kind, rows_list)`` /
``finalize(handle)`` pair (``ServingEngine``, or a fake in the pipelining
tests).

Engine-mode batchers additionally expose the ZERO-DOWNTIME SWAP SEAM the
reload plane (``deploy/``, docs/DEPLOY.md) drives: :meth:`swap_engine`
atomically reroutes future flushes under the batcher lock, every cut
flush carries its dispatching engine on the flight record (in-flight work
finalizes on the OLD engine), and :meth:`flights_on` is the retirement
signal. All access to the swappable engine attribute goes through the
lock — jaxlint JG016 polices the seam.

Observability (docs/OBSERVABILITY.md): counters/gauges and THE latency
histogram live in the process-wide telemetry registry (the per-instance
ints remain for the instance-scoped ``metrics()`` JSON), and with tracing
enabled every request leaves a correlated span chain — submit → cut →
dispatch → flight(b/e) → finalize → scatter — whose trace id is carried
on the request object across the worker/completer thread handoffs. With
tracing disabled (the default) the hot path takes one ``TRACER.enabled``
attribute read and allocates nothing.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import defaultdict, deque
from typing import Callable, Dict, Optional

import numpy as np

from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import (
    TRACER,
    current_trace_id,
    new_trace_id,
)
from gan_deeplearning4j_tpu.utils.profiling import StageStats

#: pipeline stage names — the /metrics and serve_bench breakdown schema
STAGES = ("assemble", "device", "complete")


class _KindChildren:
    """Per-kind registry series resolved once and cached in a plain dict —
    the hot path does one dict lookup per update, never a labels() parse
    (and never allocates a new series after the first request of a kind)."""

    __slots__ = ("_family", "_fixed", "_cache")

    def __init__(self, family, **fixed):
        self._family = family
        self._fixed = fixed
        self._cache: Dict[str, object] = {}

    def __call__(self, kind: str):
        child = self._cache.get(kind)
        if child is None:
            child = self._family.labels(kind=kind, **self._fixed)
            self._cache[kind] = child
        return child


@dataclasses.dataclass
class ServeResult:
    """Outcome of one request. ``status`` is always one of:

    - ``ok``          — ``data`` holds the result rows;
    - ``overloaded``  — shed at submit time, queue full (backpressure);
    - ``deadline``    — expired while queued, never ran;
    - ``error``       — the engine raised; ``error`` holds the message.

    Every submitted request gets exactly one ServeResult — the zero-lost
    invariant the bench asserts."""

    status: str
    data: Optional[np.ndarray] = None
    error: Optional[str] = None
    latency_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.status == "ok"


@dataclasses.dataclass
class _Pending:
    kind: str
    rows: np.ndarray
    deadline: float
    enqueued: float
    event: threading.Event
    result: Optional[ServeResult] = None
    # correlation id carried ACROSS the pipeline's threads explicitly (a
    # contextvar would die at the worker handoff); None while tracing is off
    trace_id: Optional[str] = None

    def finish(self, result: ServeResult) -> None:
        result.latency_s = time.monotonic() - self.enqueued
        self.result = result
        self.event.set()


class _Inflight:
    """One dispatched flush traveling from worker to completer.

    ``engine`` is the engine that DISPATCHED this flush, pinned at cut
    time: after :meth:`MicroBatcher.swap_engine` an in-flight handle must
    finalize on the engine whose staging buffers and replica ledger it
    holds — finalizing it on the new engine would recycle foreign buffers
    and release phantom in-flight reservations."""

    __slots__ = ("riders", "handle", "total_rows", "flight_id", "engine")

    def __init__(self, riders, handle, total_rows, flight_id=None,
                 engine=None):
        self.riders = riders
        self.handle = handle
        self.total_rows = total_rows
        self.flight_id = flight_id  # async-span id; None while tracing is off
        self.engine = engine  # dispatching engine; None in run_fn mode


class MicroBatcher:
    """Queue-based micro-batcher over an engine or ``run_fn``.

    The worker thread drains a bounded FIFO: it picks the oldest request's
    kind, coalesces every queued request of that kind (submission order,
    up to ``max_batch`` rows), waits out the remainder of ``max_latency``
    (measured from the oldest request) for stragglers when the batch is
    not yet full — and only cuts a batch when the in-flight window has a
    free slot. Dispatched flushes are finalized by per-replica completer
    lanes, in dispatch order within each lane. ``close()`` drains what is
    queued, then stops every thread."""

    def __init__(
        self,
        run_fn: Optional[Callable[[str, np.ndarray], np.ndarray]] = None,
        *,
        engine=None,
        max_batch: int = 128,
        max_latency: float = 0.005,
        max_queue: int = 256,
        default_timeout: float = 5.0,
        max_samples: int = 65536,
        pipeline_depth: Optional[int] = None,
        size_histogram=None,
    ):
        if (run_fn is None) == (engine is None):
            raise ValueError("pass exactly one of run_fn or engine")
        if max_batch < 1 or max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        self._run_fn = run_fn
        self._engine = engine
        if pipeline_depth is None:
            # an async engine says how deep its device pipe usefully runs
            # (ServingEngine: 2/replica on accelerators, 1/replica on CPU);
            # a synchronous run_fn has no async seam to overlap
            pipeline_depth = (
                getattr(engine, "default_pipeline_depth", None)
                or 2 * getattr(engine, "replica_count", 1)
            ) if engine else 1
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        self.pipeline_depth = pipeline_depth
        self.max_batch = max_batch
        self.max_latency = max_latency
        self.max_queue = max_queue
        self.default_timeout = default_timeout
        # flush-size histogram (serving/ladder.py): recorded per ASSEMBLED
        # flush in the worker loop — the engine pads coalesced batches,
        # not individual submits, so the ladder solver must see post-
        # coalescing sizes (a ladder solved from submit sizes measurably
        # REGRESSES under concurrency: multi-request flushes land in the
        # gaps between learned buckets). Exported via metrics(), read by
        # the reload plane to solve the next generation's bucket ladder.
        # Injectable so the mux plane can hand each variant ITS OWN
        # histogram object that survives demote/promote cycles; a
        # swap_engine keeps this same batcher, so singleton reloads carry
        # it automatically.
        if size_histogram is None:
            from gan_deeplearning4j_tpu.serving.ladder import SizeHistogram

            size_histogram = SizeHistogram()
        self.size_histogram = size_histogram

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()
        # completion lanes: one in-flight deque + completer thread per
        # replica of the INITIAL engine (run_fn mode: one lane). A swap to
        # an engine with more replicas folds extra replicas onto the
        # existing lanes (modulo) — correct, just less parallel.
        if engine is not None:
            lane_count = max(1, int(getattr(engine, "replica_count", 1) or 1))
        else:
            lane_count = 1
        self._lane_count = lane_count
        self._lanes = [deque() for _ in range(lane_count)]
        self._window_used = 0  # cut-or-dispatched flushes not yet completed
        self._closed = False
        self._worker_done = False
        self._swaps = 0
        # the flush the worker/completers are currently working OUTSIDE the
        # lock, attributed to its engine — with the lane queues these make
        # flights_on() exact, which is what engine retirement waits on
        self._dispatching_on = None
        self._finalizing_on = [None] * lane_count

        # -- counters (read under the lock; exported by metrics()) ----------
        self._submitted: Dict[str, int] = defaultdict(int)
        self._completed: Dict[str, int] = defaultdict(int)
        self._shed_overloaded = 0
        self._shed_deadline = 0
        self._errors = 0
        self._flushes = 0
        self._occupancy: Dict[int, int] = defaultdict(int)  # rows/flush -> n
        # -- telemetry registry series (docs/OBSERVABILITY.md catalogue).
        # The ints above stay per-batcher (the JSON metrics() contract is
        # instance-scoped); the registry series are the process-wide view a
        # scraper reads. Latency SAMPLES live only in the registry
        # histogram — the one stream metrics(), Prometheus, and serve_bench
        # all quote (no separate client-side collection anywhere).
        registry = get_registry()
        requests_total = registry.counter(
            "serve_requests_total", "request outcomes",
            labelnames=("kind", "status"),
        )
        self._c_request = {
            status: _KindChildren(requests_total, status=status)
            for status in ("ok", "overloaded", "deadline", "error")
        }
        self._c_latency = _KindChildren(registry.histogram(
            "serve_request_latency_seconds",
            "submit-to-result latency per request kind",
            labelnames=("kind",), max_samples=max_samples,
        ))
        self._c_flushes = registry.counter(
            "serve_flushes_total", "device flushes cut by the batcher")
        self._c_swaps = registry.counter(
            "serve_engine_swaps_total",
            "zero-downtime engine swaps performed by the batcher")
        self._c_flush_rows = registry.histogram(
            "serve_flush_rows", "rows per flush (batch occupancy)",
            max_samples=max_samples,
        )
        self._g_queue = registry.gauge(
            "serve_queue_depth", "requests waiting in the batcher queue")
        self._stages = StageStats(STAGES, max_samples=max_samples)

        self._worker = threading.Thread(
            target=self._worker_loop, name="micro-batcher", daemon=True
        )
        self._completers = [
            threading.Thread(
                target=self._completer_loop, args=(i,),
                name=f"micro-batcher-complete-{i}", daemon=True,
            )
            for i in range(lane_count)
        ]
        self._worker.start()
        for t in self._completers:
            t.start()

    # -- client side --------------------------------------------------------
    def submit(
        self, kind: str, rows: np.ndarray, timeout: Optional[float] = None
    ) -> ServeResult:
        """Block until the request completes or is shed. Bounded wait: the
        caller is back within ``timeout`` (+ scheduling noise) in EVERY
        case — full queue, expired deadline, engine error, or success."""
        timeout = self.default_timeout if timeout is None else timeout
        rows = np.asarray(rows)
        if rows.ndim != 2 or rows.shape[0] < 1:
            return ServeResult("error", error=f"expected (n, d) rows, got {rows.shape}")
        now = time.monotonic()
        req = _Pending(
            kind=kind,
            rows=rows,
            deadline=now + timeout,
            enqueued=now,
            event=threading.Event(),
        )
        if TRACER.enabled:
            # correlation id: reuse the caller's bound id (HTTP front end)
            # or mint one; it rides the request object through both
            # pipeline threads
            req.trace_id = current_trace_id() or new_trace_id()
            TRACER.instant("serve.batcher.submit", {
                "kind": kind, "rows": int(rows.shape[0]),
                "trace_id": req.trace_id,
            })
        with self._lock:
            self._submitted[kind] += 1
            if self._closed:
                self._shed_overloaded += 1
                self._c_request["overloaded"](kind).inc()
                return ServeResult("overloaded", error="batcher is closed")
            if len(self._queue) >= self.max_queue:
                # backpressure: shed NOW, in O(1) — never queue what cannot
                # be served, never block the client on a full queue
                self._shed_overloaded += 1
                self._c_request["overloaded"](kind).inc()
                return ServeResult("overloaded", error="queue full")
            self._queue.append(req)
            self._g_queue.set(len(self._queue))
            self._cv.notify_all()
        # the worker sheds expired requests, so this wait is bounded; the
        # grace covers flushes already in flight at deadline time — up to
        # pipeline_depth of them can sit ahead of this request's flush
        req.event.wait(timeout + self.max_latency + 1.0 * self.pipeline_depth)
        if req.result is None:  # worker wedged (engine hung) — still bounded
            return ServeResult("deadline", error="no result within deadline")
        return req.result

    def close(self, drain: bool = True) -> None:
        with self._lock:
            self._closed = True
            if not drain:
                while self._queue:
                    self._shed_overloaded += 1  # keep the zero-lost ledger
                    req = self._queue.popleft()
                    self._c_request["overloaded"](req.kind).inc()
                    req.finish(
                        ServeResult("overloaded", error="batcher is closed")
                    )
                self._g_queue.set(0)
            self._cv.notify_all()
        self._worker.join(timeout=10.0)
        for t in self._completers:
            t.join(timeout=10.0)

    @property
    def queue_depth(self) -> int:
        """Requests waiting in the queue right now — the cheap pressure
        signal (the mux brownout controller polls it every tick;
        ``metrics()`` would rebuild percentiles per poll)."""
        with self._lock:
            return len(self._queue)

    # -- the engine-swap seam (deploy/ reload plane) ------------------------
    @property
    def engine(self):
        """The engine NEW flushes dispatch on (None in run_fn mode). This
        lock-guarded accessor — and :meth:`swap_engine` — are the only
        places the swappable attribute may be touched (jaxlint JG016
        polices unguarded reads)."""
        with self._lock:
            return self._engine

    def swap_engine(self, engine):
        """Atomically route all FUTURE flushes to ``engine``; returns the
        previous engine. Zero-downtime by construction: flushes already
        cut or in flight carry their dispatching engine on the
        :class:`_Inflight` record and finalize on it, new cuts snapshot
        the new engine under the same lock that cuts the batch, and
        nothing is shed or drained in between. The caller retires the old
        engine once :meth:`flights_on` reports it drained."""
        if engine is None:
            raise ValueError("swap_engine needs an engine")
        if self._run_fn is not None:
            raise ValueError(
                "swap_engine requires an engine-mode batcher (run_fn mode "
                "has no engine to swap)")
        with self._lock:
            old, self._engine = self._engine, engine
            self._swaps += 1
            self._cv.notify_all()
        self._c_swaps.inc()
        return old

    def flights_on(self, engine) -> int:
        """Flushes currently owned by ``engine`` anywhere in the pipeline:
        queued between worker and completer, being dispatched, or being
        finalized. Zero means the engine's last flight has fully drained —
        the retirement condition after a swap."""
        with self._lock:
            n = sum(1 for lane in self._lanes
                    for ent in lane if ent.engine is engine)
            if self._dispatching_on is engine:
                n += 1
            n += sum(1 for fin in self._finalizing_on if fin is engine)
            return n

    # -- worker side --------------------------------------------------------
    def _take_batch(self):
        """Under the lock: wait for work AND a free in-flight slot, pick
        the oldest request's kind, and cut a same-kind batch (≤ max_batch
        rows, submission order). Reserves a window slot for the batch it
        returns."""
        while True:
            while ((not self._queue or self._window_used >= self.pipeline_depth)
                   and not self._closed):
                self._cv.wait()
            if not self._queue:
                return None  # closed and drained
            if self._window_used >= self.pipeline_depth:
                if self._closed:
                    # still drain on close — wait for the window to free up
                    self._cv.wait()
                continue
            oldest = self._queue[0]
            cut_kind = oldest.kind
            # not full yet: give stragglers a chance. Two regimes (the
            # continuous-batching policy): while the device already has
            # work in flight, a partial flush would only queue behind it —
            # hold for fullness instead (each completion re-wakes this
            # wait), but a FULL batch of ANY kind always cuts immediately
            # (it must not stall behind a partial oldest while window
            # slots sit free); once the device is hungry, wait out at most
            # the remainder of max_latency and then feed it whatever is
            # here. max_latency == 0 disables all batching delay, as
            # before.
            now = time.monotonic()
            age = now - oldest.enqueued
            if self.max_latency > 0 and not self._closed:
                kind_rows: Dict[str, int] = defaultdict(int)
                for r in self._queue:
                    kind_rows[r.kind] += r.rows.shape[0]
                if kind_rows[oldest.kind] < self.max_batch:
                    # fairness bound: once the oldest has burned half its
                    # deadline budget queued, its kind cuts NOW — neither
                    # a full batch of another kind nor a busy device may
                    # starve it further (sustained full-batch load would
                    # otherwise hold a sparse kind's partial forever)
                    overdue = age >= 0.5 * (oldest.deadline - oldest.enqueued)
                    if not overdue:
                        full = next((k for k, n in kind_rows.items()
                                     if n >= self.max_batch), None)
                        if full is not None:
                            cut_kind = full
                        elif self._window_used > 0:
                            # device fed: hold for fullness — but shed
                            # already-expired requests in place, so a hold
                            # can never pin dead entries in queue slots
                            if self._shed_expired():
                                continue
                            self._cv.wait(timeout=self.max_latency)
                            continue
                        elif age < self.max_latency:
                            self._cv.wait(timeout=self.max_latency - age)
                            continue
            if oldest.rows.shape[0] > self.max_batch:
                # a rider larger than max_batch can never coalesce: cut it
                # ALONE, now (the engine chunks it through the top bucket).
                # Skipping it for younger fitting riders would starve it
                # forever under sustained same-kind traffic.
                self._queue.popleft()
                self._g_queue.set(len(self._queue))
                self._window_used += 1
                return [oldest]
            batch, keep, total = [], deque(), 0
            for req in self._queue:
                if req.kind == cut_kind and total + req.rows.shape[0] <= self.max_batch:
                    batch.append(req)
                    total += req.rows.shape[0]
                else:
                    keep.append(req)
            if not batch:
                # cut_kind's first rider alone exceeds max_batch: cut THAT
                # rider by itself (the engine chunks it) rather than
                # falling back to the held partial oldest of another kind
                target = (oldest if cut_kind == oldest.kind else
                          next(r for r in self._queue if r.kind == cut_kind))
                batch.append(target)
                keep = deque(r for r in self._queue if r is not target)
            self._queue = keep
            self._g_queue.set(len(self._queue))
            self._window_used += 1
            return batch

    def _shed_expired(self) -> bool:
        """Under the lock: finish + remove queued requests already past
        their deadline (no device work was spent on them). True when
        anything was shed — the caller re-examines the queue."""
        now = time.monotonic()
        if not any(now > r.deadline for r in self._queue):
            return False
        keep: deque = deque()
        for req in self._queue:
            if now > req.deadline:
                self._shed_deadline += 1
                self._c_request["deadline"](req.kind).inc()
                req.finish(
                    ServeResult("deadline", error="expired while queued")
                )
            else:
                keep.append(req)
        self._queue = keep
        self._g_queue.set(len(self._queue))
        return True

    def _release_slot(self) -> None:
        with self._lock:
            self._window_used -= 1
            self._cv.notify_all()

    def _dispatch(self, engine, kind: str, rows_list):
        """Stage-A half of one flush, on the engine snapshotted AT CUT
        TIME (the swap seam: the live attribute is only read under the
        lock). For an async engine this stages, transfers, and launches
        without waiting; for a plain run_fn the handle defers ALL work to
        finalize (stage B), keeping the worker free to keep cutting
        batches."""
        if engine is not None:
            return engine.dispatch(kind, rows_list)
        return (kind, rows_list)

    def _finalize(self, engine, handle) -> np.ndarray:
        if engine is not None:
            return np.asarray(engine.finalize(handle))
        kind, rows_list = handle
        # the concatenate stays INSIDE the stage-B guard: a width-mismatched
        # rider must error its own batch, not kill the completer thread
        rows = rows_list[0] if len(rows_list) == 1 else np.concatenate(rows_list)
        return np.asarray(self._run_fn(kind, rows))

    def _worker_loop(self) -> None:
        try:
            while True:
                with self._lock:
                    batch = self._take_batch()
                    # snapshot the engine in the SAME critical section that
                    # cut the batch: a swap is atomic with respect to cuts,
                    # so every flush belongs to exactly one engine
                    engine = self._engine
                    if batch is not None:
                        self._dispatching_on = engine
                if batch is None:
                    return
                now = time.monotonic()
                live = []
                for req in batch:
                    if now > req.deadline:
                        with self._lock:
                            self._shed_deadline += 1
                        self._c_request["deadline"](req.kind).inc()
                        req.finish(
                            ServeResult("deadline", error="expired while queued")
                        )
                    else:
                        live.append(req)
                if not live:
                    with self._lock:
                        self._dispatching_on = None
                    self._release_slot()
                    continue
                flight_id = None
                if TRACER.enabled:
                    flight_id = new_trace_id()
                    TRACER.instant("serve.batcher.cut", {
                        "kind": live[0].kind, "flight": flight_id,
                        "riders": [r.trace_id for r in live],
                    })
                t0 = time.perf_counter()
                try:
                    handle = self._dispatch(
                        engine, live[0].kind, [r.rows for r in live]
                    )
                except Exception as exc:  # dispatch failure -> riders error
                    with self._lock:
                        self._errors += len(live)
                        self._dispatching_on = None
                    for req in live:
                        self._c_request["error"](req.kind).inc()
                        req.finish(ServeResult(
                            "error", error=f"{type(exc).__name__}: {exc}"))
                    self._release_slot()
                    continue
                total = sum(r.rows.shape[0] for r in live)
                # what the engine just padded: the ASSEMBLED flush, not
                # the individual submits — the ladder learner's only
                # footprint, one bounded dict increment per flush
                # (serving/ladder.py)
                self.size_histogram.record(live[0].kind, total)
                # lane = the replica this flush was routed to (stamped by
                # the engine's dispatch); run_fn handles and fakes without
                # one ride lane 0. Modulo guards a swap to a wider engine.
                # Computed BEFORE the flight span opens: nothing that can
                # raise sits between async_begin and the lane append, so
                # the span cannot be stranded open with riders unfinished.
                lane = getattr(handle, "lane", None)
                lane = 0 if lane is None else int(lane) % self._lane_count
                if flight_id is not None:
                    TRACER.complete(
                        "serve.batcher.dispatch", t0, time.perf_counter(),
                        {"kind": live[0].kind, "flight": flight_id,
                         "rows": total,
                         "riders": [r.trace_id for r in live]})
                    TRACER.async_begin("serve.flight", flight_id,
                                       {"kind": live[0].kind, "rows": total})
                with self._lock:
                    # append FIRST: once the entry is in the lane the
                    # completer owns the flight span, so a raise in the
                    # stats call below cannot strand it open
                    self._lanes[lane].append(
                        _Inflight(live, handle, total, flight_id, engine))
                    self._stages.add("assemble", time.perf_counter() - t0)
                    self._dispatching_on = None
                    self._cv.notify_all()
        finally:
            with self._lock:
                self._worker_done = True
                self._cv.notify_all()

    def _completer_loop(self, lane_idx: int) -> None:
        lane = self._lanes[lane_idx]
        while True:
            with self._lock:
                while not lane and not self._worker_done:
                    self._cv.wait()
                if not lane:
                    return  # worker exited and this lane is finalized
                ent = lane.popleft()
                self._finalizing_on[lane_idx] = ent.engine
            t0 = time.perf_counter()
            try:
                # finalize on the engine that DISPATCHED this flush — after
                # a swap the old engine's in-flight work still lands here
                out = self._finalize(ent.engine, ent.handle)
            except Exception as exc:  # engine failure -> every rider errors
                if ent.flight_id is not None:
                    TRACER.async_end("serve.flight", ent.flight_id,
                                     {"status": "error"})
                with self._lock:
                    self._errors += len(ent.riders)
                    self._finalizing_on[lane_idx] = None
                for req in ent.riders:
                    self._c_request["error"](req.kind).inc()
                    req.finish(ServeResult(
                        "error", error=f"{type(exc).__name__}: {exc}"))
                self._release_slot()
                continue
            t1 = time.perf_counter()
            offset = 0
            for req in ent.riders:
                n = req.rows.shape[0]
                req.finish(ServeResult("ok", data=out[offset:offset + n]))
                offset += n
            t2 = time.perf_counter()
            if ent.flight_id is not None:
                kind = ent.riders[0].kind
                TRACER.complete("serve.batcher.finalize", t0, t1,
                                {"kind": kind, "flight": ent.flight_id})
                TRACER.complete(
                    "serve.batcher.scatter", t1, t2,
                    {"kind": kind, "flight": ent.flight_id,
                     "riders": [r.trace_id for r in ent.riders]})
                TRACER.async_end("serve.flight", ent.flight_id,
                                 {"status": "ok"})
            with self._lock:
                self._finalizing_on[lane_idx] = None
                self._stages.add("device", t1 - t0)
                self._stages.add("complete", t2 - t1)
                self._flushes += 1
                self._c_flushes.inc()
                self._occupancy[ent.total_rows] += 1
                self._c_flush_rows.observe(ent.total_rows)
                for req in ent.riders:
                    self._completed[req.kind] += 1
                    self._c_request["ok"](req.kind).inc()
                    self._c_latency(req.kind).observe(req.result.latency_s)
            self._release_slot()

    # -- observability ------------------------------------------------------
    def metrics(self) -> dict:
        """Counter snapshot + latency percentiles + occupancy histogram +
        per-stage pipeline breakdown — the /metrics payload schema
        (docs/SERVING.md)."""
        # latency percentiles come from the ONE registry histogram stream
        # (serve_request_latency_seconds) — the same numbers a Prometheus
        # scrape and a serve_bench artifact quote. list() snapshots the
        # child cache in one GIL-atomic step: the pipeline threads insert a
        # kind's child concurrently with a scrape, and iterating the live
        # dict would raise mid-resize
        lat = {
            kind: {
                k: v * 1e3 for k, v in child.percentiles().items()
            }
            for kind, child in list(self._c_latency._cache.items())
        }
        with self._lock:
            return {
                "submitted": dict(self._submitted),
                "completed": dict(self._completed),
                "shed_overloaded": self._shed_overloaded,
                "shed_deadline": self._shed_deadline,
                "errors": self._errors,
                "flushes": self._flushes,
                "engine_swaps": self._swaps,
                "queue_depth": len(self._queue),
                "batch_occupancy": {str(k): v for k, v in sorted(self._occupancy.items())},
                "flush_sizes": self.size_histogram.stats(),
                "latency_ms": lat,
                "pipeline": {
                    "depth": self.pipeline_depth,
                    "in_flight": self._window_used,
                    "lanes": self._lane_count,
                    "mode": "engine" if self._engine is not None else "run_fn",
                    "stage_ms": self._stages.summary_ms(),
                    "stage_occupancy": self._stages.occupancy(),
                },
            }
