"""Dynamic micro-batcher — the serving throughput lever (μ-cuDNN-style).

Single-request dispatch wastes an accelerator: a batch-1 forward pays the
same dispatch latency as batch-128 for ~1% of the useful work. This module
coalesces concurrent requests of the same kind into one device batch under
two triggers — a full batch (``max_batch`` rows) or the oldest request
aging past ``max_latency`` — the classic throughput/latency trade of
server-side batching (*TensorFlow: a system for large-scale ML*, §4.3).

Backpressure is explicit, not emergent: the queue is bounded, and a submit
against a full queue returns an ``overloaded`` result IMMEDIATELY instead
of blocking or growing the queue without bound — under overload a serving
tier must shed load in O(1), because every queued request it cannot serve
within its deadline is work thrown away *after* paying for it. Requests
that expire while queued are likewise shed with ``deadline`` before any
device work is spent on them.

Pure stdlib (threading/collections): no jax import, so the batching policy
is unit-testable with a fake engine and reusable for any ``run_fn``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import defaultdict, deque
from typing import Callable, Dict, Optional

import numpy as np

from gan_deeplearning4j_tpu.utils.profiling import percentiles


@dataclasses.dataclass
class ServeResult:
    """Outcome of one request. ``status`` is always one of:

    - ``ok``          — ``data`` holds the result rows;
    - ``overloaded``  — shed at submit time, queue full (backpressure);
    - ``deadline``    — expired while queued, never ran;
    - ``error``       — the engine raised; ``error`` holds the message.

    Every submitted request gets exactly one ServeResult — the zero-lost
    invariant the bench asserts."""

    status: str
    data: Optional[np.ndarray] = None
    error: Optional[str] = None
    latency_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.status == "ok"


@dataclasses.dataclass
class _Pending:
    kind: str
    rows: np.ndarray
    deadline: float
    enqueued: float
    event: threading.Event
    result: Optional[ServeResult] = None

    def finish(self, result: ServeResult) -> None:
        result.latency_s = time.monotonic() - self.enqueued
        self.result = result
        self.event.set()


class MicroBatcher:
    """Queue-based micro-batcher over a ``run_fn(kind, rows) -> rows``.

    One worker thread drains a bounded FIFO: it picks the oldest request's
    kind, coalesces every queued request of that kind (submission order,
    up to ``max_batch`` rows), and waits out the remainder of
    ``max_latency`` (measured from the oldest request) for stragglers when
    the batch is not yet full. ``close()`` drains what is queued, then
    stops the worker."""

    def __init__(
        self,
        run_fn: Callable[[str, np.ndarray], np.ndarray],
        *,
        max_batch: int = 128,
        max_latency: float = 0.005,
        max_queue: int = 256,
        default_timeout: float = 5.0,
        max_samples: int = 65536,
    ):
        if max_batch < 1 or max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        self._run_fn = run_fn
        self.max_batch = max_batch
        self.max_latency = max_latency
        self.max_queue = max_queue
        self.default_timeout = default_timeout

        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._closed = False

        # -- counters (read under the lock; exported by metrics()) ----------
        self._submitted: Dict[str, int] = defaultdict(int)
        self._completed: Dict[str, int] = defaultdict(int)
        self._shed_overloaded = 0
        self._shed_deadline = 0
        self._errors = 0
        self._flushes = 0
        self._occupancy: Dict[int, int] = defaultdict(int)  # rows/flush -> n
        self._latencies: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=max_samples)
        )

        self._worker = threading.Thread(
            target=self._loop, name="micro-batcher", daemon=True
        )
        self._worker.start()

    # -- client side --------------------------------------------------------
    def submit(
        self, kind: str, rows: np.ndarray, timeout: Optional[float] = None
    ) -> ServeResult:
        """Block until the request completes or is shed. Bounded wait: the
        caller is back within ``timeout`` (+ scheduling noise) in EVERY
        case — full queue, expired deadline, engine error, or success."""
        timeout = self.default_timeout if timeout is None else timeout
        rows = np.asarray(rows)
        if rows.ndim != 2 or rows.shape[0] < 1:
            return ServeResult("error", error=f"expected (n, d) rows, got {rows.shape}")
        now = time.monotonic()
        req = _Pending(
            kind=kind,
            rows=rows,
            deadline=now + timeout,
            enqueued=now,
            event=threading.Event(),
        )
        with self._lock:
            self._submitted[kind] += 1
            if self._closed:
                self._shed_overloaded += 1
                return ServeResult("overloaded", error="batcher is closed")
            if len(self._queue) >= self.max_queue:
                # backpressure: shed NOW, in O(1) — never queue what cannot
                # be served, never block the client on a full queue
                self._shed_overloaded += 1
                return ServeResult("overloaded", error="queue full")
            self._queue.append(req)
            self._nonempty.notify()
        # the worker sheds expired requests, so this wait is bounded; the
        # grace covers a flush already in flight at deadline time
        req.event.wait(timeout + self.max_latency + 1.0)
        if req.result is None:  # worker wedged (engine hung) — still bounded
            return ServeResult("deadline", error="no result within deadline")
        return req.result

    def close(self, drain: bool = True) -> None:
        with self._lock:
            self._closed = True
            if not drain:
                while self._queue:
                    self._shed_overloaded += 1  # keep the zero-lost ledger
                    self._queue.popleft().finish(
                        ServeResult("overloaded", error="batcher is closed")
                    )
            self._nonempty.notify()
        self._worker.join(timeout=10.0)

    # -- worker side --------------------------------------------------------
    def _take_batch(self):
        """Under the lock: wait for work, pick the oldest request's kind,
        and cut a same-kind batch (≤ max_batch rows, submission order)."""
        while True:
            while not self._queue and not self._closed:
                self._nonempty.wait()
            if not self._queue:
                return None  # closed and drained
            oldest = self._queue[0]
            # not full yet and still young: give stragglers a chance
            age = time.monotonic() - oldest.enqueued
            if age < self.max_latency and not self._closed:
                same = sum(
                    r.rows.shape[0] for r in self._queue if r.kind == oldest.kind
                )
                if same < self.max_batch:
                    self._nonempty.wait(timeout=self.max_latency - age)
                    continue
            batch, keep, total = [], deque(), 0
            for req in self._queue:
                if req.kind == oldest.kind and total + req.rows.shape[0] <= self.max_batch:
                    batch.append(req)
                    total += req.rows.shape[0]
                else:
                    keep.append(req)
            if not batch:  # oldest alone exceeds max_batch — take it anyway
                batch.append(oldest)
                keep = deque(r for r in self._queue if r is not oldest)
            self._queue = keep
            return batch

    def _loop(self) -> None:
        while True:
            with self._lock:
                batch = self._take_batch()
            if batch is None:
                return
            now = time.monotonic()
            live = []
            for req in batch:
                if now > req.deadline:
                    with self._lock:
                        self._shed_deadline += 1
                    req.finish(
                        ServeResult("deadline", error="expired while queued")
                    )
                else:
                    live.append(req)
            if not live:
                continue
            try:
                # the concatenate stays INSIDE the guard: a width-mismatched
                # rider must error its own batch, not kill the worker thread
                rows = (
                    live[0].rows
                    if len(live) == 1
                    else np.concatenate([r.rows for r in live])
                )
                out = np.asarray(self._run_fn(live[0].kind, rows))
            except Exception as exc:  # engine failure -> every rider errors
                with self._lock:
                    self._errors += len(live)
                for req in live:
                    req.finish(ServeResult("error", error=f"{type(exc).__name__}: {exc}"))
                continue
            with self._lock:
                self._flushes += 1
                self._occupancy[rows.shape[0]] += 1
            offset = 0
            for req in live:
                n = req.rows.shape[0]
                req.finish(ServeResult("ok", data=out[offset:offset + n]))
                offset += n
                with self._lock:
                    self._completed[req.kind] += 1
                    self._latencies[req.kind].append(req.result.latency_s)

    # -- observability ------------------------------------------------------
    def metrics(self) -> dict:
        """Counter snapshot + latency percentiles + occupancy histogram —
        the /metrics payload schema (docs/SERVING.md)."""
        with self._lock:
            lat = {
                kind: {
                    k: v * 1e3 for k, v in percentiles(samples).items()
                }
                for kind, samples in self._latencies.items()
            }
            return {
                "submitted": dict(self._submitted),
                "completed": dict(self._completed),
                "shed_overloaded": self._shed_overloaded,
                "shed_deadline": self._shed_deadline,
                "errors": self._errors,
                "flushes": self._flushes,
                "queue_depth": len(self._queue),
                "batch_occupancy": {str(k): v for k, v in sorted(self._occupancy.items())},
                "latency_ms": lat,
            }
