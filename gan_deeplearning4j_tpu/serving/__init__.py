"""serving/ — batched inference for the trained artifacts (SURVEY §0).

The paper's end product is not the training loop but what it leaves behind:
a generator used only for sampling and a 10-class classifier built on the
discriminator's learned features. This package is the deploy surface the
reference never had — it loads serializer checkpoints and answers three
request types (sample-from-z, classify-image, extract-discriminator-
features) through one dynamic micro-batcher:

- :mod:`.engine` — restores ``ComputationGraph``s from checkpoint zips,
  AOT-compiles one executable per (request kind, padded batch bucket,
  replica) so arbitrary request sizes never trigger a fresh XLA compile
  (eager warmup makes that true from the first request), pins the weights
  on every replica once, assembles batches through pinned staging buffers
  (no per-call pad alloc/concat), and routes flushes across replicas —
  with a mesh-sharded bulk lane for oversized single-caller batches;
- :mod:`.batcher` — a queue-based micro-batcher with max-latency / max-batch
  triggers, continuous-batching scheduling (hold for fullness while the
  device is busy), a bounded two-stage dispatch/completion pipeline that
  overlaps host assembly with device execution, per-request deadlines,
  backpressure (bounded queue that sheds with an explicit "overloaded"
  result instead of growing without bound), and the zero-downtime
  engine-swap seam the reload plane (``deploy/``, docs/DEPLOY.md) drives:
  ``swap_engine`` reroutes future flushes atomically while in-flight
  flights finalize on the engine that dispatched them;
- :mod:`.service` — the in-process API plus a stdlib-only HTTP JSON
  endpoint with ``/healthz`` and ``/metrics`` (JSON or ``?format=prom``
  Prometheus text), the served bundle's ``generation``, and the telemetry
  debug hooks (``POST /debug/trace`` device captures, ``GET /debug/spans``
  Chrome trace export — docs/OBSERVABILITY.md);
- :mod:`.ladder` — traffic-shaped bucket ladders: a bounded flush-size
  histogram recorded as the batcher assembles each flush, an exact DP
  (:func:`~.ladder.solve_ladder`) choosing ≤K buckets that minimize
  expected padded-rows waste, and manifest persistence so a reloaded
  generation boots with buckets learned from live traffic instead of
  the 1/8/32/128 default;
- ``python -m gan_deeplearning4j_tpu.serving`` — the server CLI;
- :mod:`.mux` — the multi-model multiplexing plane (docs/MULTIPLEX.md):
  N named variants behind deterministic weighted traffic splitting, a
  continuous canary ramp with SLO auto-rollback, shared-pool engine
  residency under a budget, and per-model brownout tiering (imported
  explicitly — ``from gan_deeplearning4j_tpu.serving.mux import ...`` —
  so the singleton server never pays for it).

Architecture notes: docs/SERVING.md.
"""

from gan_deeplearning4j_tpu.serving.batcher import MicroBatcher, ServeResult
from gan_deeplearning4j_tpu.serving.engine import ServingEngine
from gan_deeplearning4j_tpu.serving.ladder import (
    SizeHistogram,
    expected_waste,
    manifest_ladder,
    solve_ladder,
    write_ladder_block,
)
from gan_deeplearning4j_tpu.serving.service import InferenceService, make_server

__all__ = [
    "MicroBatcher",
    "ServeResult",
    "ServingEngine",
    "InferenceService",
    "make_server",
    "SizeHistogram",
    "solve_ladder",
    "expected_waste",
    "manifest_ladder",
    "write_ladder_block",
]
