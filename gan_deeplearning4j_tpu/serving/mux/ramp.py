"""RampController — continuous canary traffic ramp with auto-rollback.

The deploy plane's canary gate (docs/DEPLOY.md) makes ONE admission
decision: probe the candidate, then swap 100% of traffic. This module
generalizes that into the continuous form the fleet item queued
(ROADMAP: "1% → 50% → 100%"): an adopted candidate variant walks a stage
ladder of traffic fractions (default 1% → 10% → 50% → 100%), holding
each stage until the candidate has *positively demonstrated* health,
and rolling ALL of its traffic back on an SLO burn.

The health signal is three-valued, and the asymmetry is the point:

- **True (healthy evidence)** — counts toward the ``hold_ticks`` streak
  that advances the stage. Advancing requires data: the fail-closed rule
  of ``telemetry/slo.py`` applies to *promotion*.
- **False (burning)** — rolls back IMMEDIATELY: candidate weight to 0,
  every other variant restored to its pre-ramp weight (captured at
  ``start()``), state ``rolled_back``. One bad window un-does the whole
  ramp — re-running it is cheap, serving a burning variant at 50% is
  not.
- **None (no data)** — holds: neither advance nor rollback. An empty
  window must not *promote* a candidate (no data is not health), but it
  must not *kill* one either — at a 1% stage the candidate's window is
  legitimately sparse, and rolling back on silence would make small
  first stages impossible.

Stage weights are set through the registry's atomic ``set_weights`` so a
transition is never observed half-applied: at fraction ``f`` the
candidate's weight is chosen so its rendezvous *share* is exactly ``f``
against the captured base weights (``f = 1`` retires the bases to 0 —
the candidate has taken over; completing a ramp IS the new primary
election). The controller is passive — ``tick()`` is driven by the mux
service's control loop, the drill, or an operator."""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, Optional, Sequence

from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import TRACER

DEFAULT_STAGES = (0.01, 0.10, 0.50, 1.0)

#: ramp states (mux_ramp_state gauge exports the index)
STATES = ("idle", "ramping", "complete", "rolled_back")
_STATE_CODE = {name: i for i, name in enumerate(STATES)}


def health_from_tracker(tracker, threshold: float = 1.0,
                        window: str = "fast") -> Callable[[], Optional[bool]]:
    """The default ramp signal from a per-variant SLO tracker: False when
    any objective's ``window`` burn rate is at/over ``threshold`` (real
    evidence of burn), None when every burn is NaN (no data — hold),
    True otherwise."""

    def health() -> Optional[bool]:
        rates = tracker.burn_rates()
        burns = [windows[window] for windows in rates.values()]
        if any(not math.isnan(b) and b >= threshold for b in burns):
            return False
        if all(math.isnan(b) for b in burns):
            return None
        return True

    return health


class RampController:
    """Walks ``candidate`` up ``stages`` of traffic share inside a
    :class:`~.registry.MuxRegistry` (module docstring).

    ``health`` is the three-valued signal (:func:`health_from_tracker`
    builds one from an SLOTracker); ``hold_ticks`` is how many
    consecutive healthy ticks each stage must bank before advancing."""

    def __init__(self, registry, candidate: str, *,
                 stages: Sequence[float] = DEFAULT_STAGES,
                 hold_ticks: int = 2,
                 health: Optional[Callable[[], Optional[bool]]] = None):
        stages = tuple(float(s) for s in stages)
        if not stages or any(not 0.0 < s <= 1.0 for s in stages):
            raise ValueError(
                f"stages must be fractions in (0, 1], got {stages!r}")
        if list(stages) != sorted(stages):
            raise ValueError("stages must be non-decreasing")
        if hold_ticks < 1:
            raise ValueError("hold_ticks must be >= 1")
        self.registry = registry
        self.candidate = str(candidate)
        self.stages = stages
        self.hold_ticks = int(hold_ticks)
        self._health = health or (lambda: True)
        self._lock = threading.Lock()
        self._state = "idle"
        self._stage_idx = -1
        self._streak = 0
        self._base_weights: Dict[str, float] = {}
        self._rollbacks = 0
        self.events: list = []
        registry_m = get_registry()
        self._g_stage = registry_m.gauge(
            "mux_ramp_fraction",
            "candidate traffic fraction of the active ramp stage "
            "(-1 = no ramp running)", labelnames=("model",))
        self._g_state = registry_m.gauge(
            "mux_ramp_state",
            "ramp state: 0=idle 1=ramping 2=complete 3=rolled_back",
            labelnames=("model",))
        self._c_rollbacks = registry_m.counter(
            "mux_ramp_rollbacks_total",
            "ramps auto-rolled-back on an SLO burn", labelnames=("model",))
        self._g_stage.labels(model=self.candidate).set(-1.0)
        self._g_state.labels(model=self.candidate).set(_STATE_CODE["idle"])

    # -- weight math ------------------------------------------------------
    def _apply_fraction(self, fraction: float) -> None:
        """Set weights so the candidate's rendezvous share is exactly
        ``fraction`` against the captured base weights."""
        base = {n: w for n, w in self._base_weights.items()
                if n != self.candidate}
        if fraction >= 1.0:
            weights = {n: 0.0 for n in base}
            weights[self.candidate] = 1.0
        else:
            total = sum(w for w in base.values() if w > 0.0)
            if total <= 0.0:
                # no weighted incumbent: the candidate IS the traffic
                weights = {self.candidate: 1.0}
            else:
                weights = dict(base)
                weights[self.candidate] = fraction * total / (1.0 - fraction)
        self.registry.set_weights(weights)
        self._g_stage.labels(model=self.candidate).set(fraction)

    def _transition(self, state: str) -> None:
        self._state = state
        self._g_state.labels(model=self.candidate).set(_STATE_CODE[state])

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        """Capture the pre-ramp weights and enter the first stage. The
        candidate must be registered; it is warmed by the registry when
        its first stage weight lands (``set_weights`` warms cold
        variants gaining weight)."""
        with self._lock:
            if self._state == "ramping":
                raise RuntimeError("ramp already running")
            self._base_weights = self.registry.splitter.weights()
            self._stage_idx = 0
            self._streak = 0
            self._transition("ramping")
            self.events.append({"event": "start",
                                "stages": list(self.stages)})
        self._apply_fraction(self.stages[0])
        TRACER.instant("mux.ramp.start", {
            "candidate": self.candidate, "fraction": self.stages[0]})

    def tick(self) -> str:
        """One control-loop step (module docstring's three-valued rule).
        Returns the state after the step."""
        with self._lock:
            if self._state != "ramping":
                return self._state
            stage_idx = self._stage_idx
        healthy = self._health()
        if healthy is False:
            return self._rollback()
        if healthy is None:
            return "ramping"  # no data: hold, neither advance nor kill
        with self._lock:
            if self._state != "ramping" or self._stage_idx != stage_idx:
                return self._state  # raced a concurrent rollback/advance
            self._streak += 1
            if self._streak < self.hold_ticks:
                return "ramping"
            self._streak = 0
            self._stage_idx += 1
            done = self._stage_idx >= len(self.stages)
            if done:
                self._transition("complete")
                self.events.append({"event": "complete"})
            else:
                fraction = self.stages[self._stage_idx]
                self.events.append({"event": "advance",
                                    "fraction": fraction})
        if done:
            # the ladder is banked: the candidate takes all traffic (a
            # ladder ending below 1.0 completes AT its final fraction)
            if self.stages[-1] >= 1.0:
                self._apply_fraction(1.0)
            TRACER.instant("mux.ramp.complete", {
                "candidate": self.candidate})
            return "complete"
        self._apply_fraction(fraction)
        TRACER.instant("mux.ramp.advance", {
            "candidate": self.candidate, "fraction": fraction})
        return "ramping"

    def _rollback(self) -> str:
        with self._lock:
            if self._state != "ramping":
                return self._state
            restore = dict(self._base_weights)
            restore[self.candidate] = 0.0
            self._rollbacks += 1
            self._transition("rolled_back")
            self.events.append({"event": "rollback",
                                "stage_fraction":
                                    self.stages[self._stage_idx]})
        # warm=True: an incumbent the residency budget evicted mid-ramp
        # must come BACK when its weight is restored (set_weights applies
        # the weights first, so the restore itself is never delayed by
        # the re-warm)
        self.registry.set_weights(restore)
        self._g_stage.labels(model=self.candidate).set(-1.0)
        self._c_rollbacks.labels(model=self.candidate).inc()
        TRACER.instant("mux.ramp.rollback", {"candidate": self.candidate})
        return "rolled_back"

    # -- observability ----------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def rollbacks(self) -> int:
        with self._lock:
            return self._rollbacks

    def snapshot(self) -> dict:
        with self._lock:
            idx = self._stage_idx
            return {
                "candidate": self.candidate,
                "state": self._state,
                "stages": list(self.stages),
                "stage_index": idx,
                "fraction": (self.stages[idx]
                             if self._state == "ramping"
                             and 0 <= idx < len(self.stages) else None),
                "streak": self._streak,
                "hold_ticks": self.hold_ticks,
                "rollbacks": self._rollbacks,
            }
