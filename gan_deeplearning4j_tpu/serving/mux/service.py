"""MuxService — the multi-model request surface over a MuxRegistry.

Duck-types :class:`~..service.InferenceService`'s handler contract, so
the same stdlib HTTP front end (``serving.service.make_server``) serves
it. What changes is WHO answers: every ``/v1/*`` request carries a
routing key (``"key"`` in the payload — a user/session id — or a minted
one when absent), the :class:`~.splitter.WeightedSplitter` resolves it
to a variant, and that variant's micro-batcher runs the batch. The
response names the serving ``model``, so a client can see which side of
a ramp it landed on.

Per-model degradation (docs/MULTIPLEX.md "Brownout tiering"): under
overload the PR 12 router sheds *work shapes* (oversized slabs); the mux
plane sheds *models*, most expensive first. Brownout level L sheds new
traffic of the L highest-``cost`` variants with honest 503s while the
cheap (bf16) variants keep answering — degradation follows the cost
gradient instead of hitting every model equally. The built-in
:class:`BrownoutController` drives the level from aggregate queue
pressure with enter/exit hysteresis (the same fail-safe shape as the
autoscaler's brownout: pressure alone, never latched by its own sheds);
``POST /mux/brownout`` overrides it manually.

Observability: every outcome lands in per-model registry series
(``mux_requests_total{model,kind,status}``,
``mux_request_latency_seconds{model}``) AND a per-variant
:class:`~...telemetry.slo.SLOTracker` (``mux_slo_*{model,...}``) — the
per-variant burn rate is what the ramp controller's auto-rollback reads.
The ``/metrics`` payload keeps the single-model worker's top-level
``queue_depth`` and ``pipeline.in_flight`` keys (summed across
variants), so the fleet autoscaler's pressure signal reads a mux worker
exactly like a singleton one (docs/FLEET.md "Autoscaling")."""

from __future__ import annotations

import logging
import threading
import uuid
from typing import Dict, Optional, Tuple

import numpy as np

from gan_deeplearning4j_tpu.serving.mux.ramp import (
    RampController,
    health_from_tracker,
)
from gan_deeplearning4j_tpu.serving.mux.registry import MuxRegistry
from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.slo import SLOConfig, SLOTracker
from gan_deeplearning4j_tpu.telemetry.trace import (
    TRACER,
    bind_trace_id,
    new_trace_id,
    sanitize_trace_id,
    unbind_trace_id,
)
from urllib.parse import parse_qs

logger = logging.getLogger(__name__)

_STATUS_HTTP = {"ok": 200, "overloaded": 503, "deadline": 503, "error": 500}


class BrownoutController:
    """Pressure-driven per-model brownout tiers with hysteresis.

    ``tick(pressure)``: pressure at/over ``threshold`` for
    ``enter_ticks`` consecutive ticks raises the level (one more —
    the next most expensive — variant sheds); calm for ``exit_ticks``
    lowers it tier-by-tier. The level never reaches the variant count —
    the cheapest variant always serves (shedding everything is an
    outage, not a degradation)."""

    def __init__(self, *, threshold: float = 0.8, enter_ticks: int = 2,
                 exit_ticks: int = 4):
        if threshold <= 0:
            raise ValueError("threshold must be > 0")
        if enter_ticks < 1 or exit_ticks < 1:
            raise ValueError("enter_ticks and exit_ticks must be >= 1")
        self.threshold = threshold
        self.enter_ticks = enter_ticks
        self.exit_ticks = exit_ticks
        self._hot = 0
        self._calm = 0

    def tick(self, pressure: float, level: int, max_level: int) -> int:
        """The next level given current ``pressure`` (NaN fails closed:
        evidence of neither overload nor calm — hold the level)."""
        if not np.isfinite(pressure):
            self._hot = self._calm = 0
            return level
        if pressure >= self.threshold:
            self._hot += 1
            self._calm = 0
            if self._hot >= self.enter_ticks and level < max_level:
                self._hot = 0
                return level + 1
        else:
            self._calm += 1
            self._hot = 0
            if self._calm >= self.exit_ticks and level > 0:
                self._calm = 0
                return level - 1
        return level


class MuxService:
    """The in-process multi-model serving API (module docstring)."""

    def __init__(self, registry: MuxRegistry, *,
                 slo_config: Optional[SLOConfig] = None,
                 brownout: Optional[BrownoutController] = None,
                 alerts=None):
        """``alerts`` is an optional
        :class:`~...telemetry.alerts.AlertManager` — typically over
        :func:`~...telemetry.alerts.default_mux_rules`, whose burn and
        queue rules read the per-model labeled families and therefore
        fan out into one alert instance per variant (per-model scoping;
        docs/MULTIPLEX.md "Alerting"). The control loop ticks its
        evaluation over this process's registry snapshot; ``GET
        /alerts`` serves it. None = zero alerting cost."""
        self.registry = registry
        self.alerts = alerts
        self.draining = False
        self._slo_config = slo_config
        self._lock = threading.Lock()
        self._trackers: Dict[str, SLOTracker] = {}
        self._brownout_level = 0
        self._brownout_auto = brownout or BrownoutController()
        self._ramp: Optional[RampController] = None
        self._loop_stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        metrics = get_registry()
        requests = metrics.counter(
            "mux_requests_total", "mux request outcomes per variant",
            labelnames=("model", "kind", "status"))
        self._c_requests = requests.labels
        self._h_latency = metrics.histogram(
            "mux_request_latency_seconds",
            "submit-to-result latency per serving variant",
            labelnames=("model",))
        self._g_queue = metrics.gauge(
            "mux_queue_depth", "queued requests per resident variant",
            labelnames=("model",))
        self._g_brownout = metrics.gauge(
            "mux_brownout_level",
            "per-model brownout tier: the L most expensive variants shed "
            "(0 = off)")
        self._g_brownout.set(0.0)
        self._c_brownout_sheds = metrics.counter(
            "mux_brownout_sheds_total",
            "requests shed because their variant is browned out",
            labelnames=("model",))

    # -- per-variant SLO --------------------------------------------------
    def tracker_for(self, name: str) -> SLOTracker:
        with self._lock:
            tracker = self._trackers.get(name)
            if tracker is None:
                tracker = SLOTracker(self._slo_config,
                                     metric_prefix="mux",
                                     labels={"model": name})
                self._trackers[name] = tracker
        return tracker

    def _trackers_snapshot(self) -> list:
        """Sorted (name, tracker) pairs, snapshotted under the lock so
        healthz never iterates the dict while tracker_for is inserting."""
        with self._lock:
            return sorted(self._trackers.items())

    # -- brownout ---------------------------------------------------------
    @property
    def brownout_level(self) -> int:
        with self._lock:
            return self._brownout_level

    def _ranked_weighted(self) -> list:
        """Traffic-carrying variants (positive weight), most expensive
        first (ties by name — deterministic). Zero-weight variants are
        excluded: shedding a variant that serves nothing relieves
        nothing, and counting them toward the tier ceiling could let a
        tier silence EVERY weighted variant — a total outage dressed as
        degradation."""
        weights = self.registry.splitter.weights()
        return sorted(
            ((n, c) for n, c in self.registry.costs().items()
             if weights.get(n, 0.0) > 0.0),
            key=lambda kv: (-kv[1], kv[0]))

    def _max_level(self) -> int:
        return max(0, len(self._ranked_weighted()) - 1)

    def set_brownout(self, level: int) -> int:
        """Clamp + set the per-model brownout tier. Level L sheds the L
        most expensive traffic-carrying variants' new traffic; the
        cheapest weighted variant never sheds (and :meth:`_shed_set`
        re-clamps per request, so a weight change after the level was
        set can never silence the whole pool)."""
        level = max(0, min(self._max_level(), int(level)))
        with self._lock:
            changed = level != self._brownout_level
            self._brownout_level = level
        self._g_brownout.set(float(level))
        if changed:
            logger.warning("mux brownout level set to %d", level)
        return level

    def _shed_set(self) -> set:
        """The variants whose traffic the current tier sheds: the
        ``level`` most expensive *weighted* variants — clamped against
        the CURRENT weights, so the cheapest traffic-carrying variant
        always serves no matter how the weights moved since the level
        was set."""
        with self._lock:
            level = self._brownout_level
        if level < 1:
            return set()
        ranked = self._ranked_weighted()
        level = min(level, max(0, len(ranked) - 1))
        return {name for name, _ in ranked[:level]}

    def _pressure(self) -> float:
        """Aggregate queue pressure across resident variants: total
        queued / total queue capacity. NaN when nothing is resident.
        Non-resident variants' queue gauges are zeroed here — a demoted
        variant has no queue, and a gauge frozen at its last pre-demote
        value would read as phantom pressure on a dashboard."""
        total = capacity = 0
        resident = set(self.registry.resident_names())
        for name in self.registry.names():
            batcher = (self.registry.batcher_for(name)
                       if name in resident else None)
            if batcher is None:
                self._g_queue.labels(model=name).set(0.0)
                continue
            depth = batcher.queue_depth
            total += depth
            capacity += batcher.max_queue
            self._g_queue.labels(model=name).set(float(depth))
        return (total / capacity) if capacity else float("nan")

    # -- ramp -------------------------------------------------------------
    def start_ramp(self, candidate: str, *, stages=None,
                   hold_ticks: int = 2, health=None,
                   rollback_threshold: float = 1.0) -> RampController:
        """Start a continuous canary ramp for ``candidate``; the health
        signal defaults to the candidate's own per-variant SLO burn
        (:func:`~.ramp.health_from_tracker`)."""
        if health is None:
            health = health_from_tracker(self.tracker_for(candidate),
                                         threshold=rollback_threshold)
        kwargs = {"hold_ticks": hold_ticks, "health": health}
        if stages is not None:
            kwargs["stages"] = stages
        ramp = RampController(self.registry, candidate, **kwargs)
        with self._lock:
            if self._ramp is not None and self._ramp.state == "ramping":
                raise RuntimeError(
                    f"a ramp for {self._ramp.candidate!r} is already "
                    f"running")
            self._ramp = ramp
        ramp.start()
        return ramp

    @property
    def ramp(self) -> Optional[RampController]:
        with self._lock:
            return self._ramp

    # -- control loop -----------------------------------------------------
    def control_tick(self) -> None:
        """One control step: advance/rollback the active ramp, and walk
        the brownout tier from queue pressure. Driven by
        :meth:`start_control_loop` or directly (tests, the drill)."""
        ramp = self.ramp
        if ramp is not None:
            ramp.tick()
        pressure = self._pressure()
        level = self._brownout_auto.tick(
            pressure, self.brownout_level, self._max_level())
        if level != self.brownout_level:
            self.set_brownout(level)
        if self.alerts is not None:
            # per-model alerting rides the control tick the service
            # already runs — same no-extra-scrape contract as the fleet
            # plane (the per-model families are in THIS registry). The
            # burn-rate gauges only move when a tracker snapshots, so
            # refresh every variant's stream first.
            try:
                with self._lock:
                    trackers = list(self._trackers.values())
                for tracker in trackers:
                    tracker.snapshot()
                self.alerts.evaluate(
                    get_registry().snapshot(include_samples=True))
            except Exception:
                logger.exception("mux alert evaluation failed")

    def start_control_loop(self, interval: float = 0.25) -> threading.Thread:
        with self._lock:
            if self._loop_thread is not None and self._loop_thread.is_alive():
                return self._loop_thread
            self._loop_stop.clear()
            t = threading.Thread(target=self._control_loop,
                                 args=(interval,), name="mux-control",
                                 daemon=True)
            self._loop_thread = t
        t.start()
        return t

    def _control_loop(self, interval: float) -> None:
        while not self._loop_stop.is_set():
            try:
                self.control_tick()
            except Exception:  # a control bug must not kill the loop
                logger.exception("mux control tick failed")
            self._loop_stop.wait(interval)

    # -- observability ----------------------------------------------------
    def healthz(self) -> dict:
        snap = self.registry.snapshot()
        resident = [v for v in snap["variants"].values() if v["resident"]]
        if self.draining:
            status = "draining"
        elif not resident:
            status = "down"
        elif all(v["warm"] for v in resident):
            status = "ok"
        else:
            status = "warming"
        kinds: set = set()
        for name in self.registry.resident_names():
            engine = self.registry.engine_for(name)
            if engine is not None:
                kinds.update(engine.kinds)
        level = self.brownout_level
        primary = self.registry.primary_name()
        ramp = self.ramp
        body = {
            "status": status,
            "role": "mux",
            "kinds": sorted(kinds),
            "generation": (snap["variants"][primary]["generation"]
                           if primary else None),
            "primary": primary,
            "variants": snap["variants"],
            "shares": snap["shares"],
            "brownout": {"active": level > 0, "level": level,
                         "shedding": sorted(self._shed_set())},
            # the economics the shed/evict order runs on, with provenance:
            # "measured" = live-ladder quant/cost.py block, "declared" =
            # operator bootstrap (docs/QUANT.md)
            "costs": {
                name: {
                    "cost": v["cost"],
                    "cost_source": v["cost_source"],
                    "declared_cost": v["declared_cost"],
                    "measured_cost": v["measured_cost"],
                    "resident_param_bytes": v["resident_param_bytes"],
                    "precision": v["precision"],
                }
                for name, v in sorted(snap["variants"].items())
            },
            "ramp": None if ramp is None else ramp.snapshot(),
            "slo": {name: tracker.snapshot()
                    for name, tracker in self._trackers_snapshot()},
        }
        if self.alerts is not None:
            body["alerts"] = self.alerts.health_block()
        return body

    def metrics(self) -> dict:
        """Aggregate + per-variant metrics. Top-level ``queue_depth`` /
        ``pipeline.in_flight`` keep the single-model schema summed
        across variants, so the fleet router's scrape and the
        autoscaler's pressure math work unchanged over a mux worker."""
        per_variant: Dict[str, dict] = {}
        queue_depth = in_flight = 0
        depth_total = 0
        for name in self.registry.resident_names():
            batcher = self.registry.batcher_for(name)
            if batcher is None:
                continue
            m = batcher.metrics()
            per_variant[name] = m
            queue_depth += m["queue_depth"]
            in_flight += m["pipeline"]["in_flight"]
            depth_total += m["pipeline"]["depth"]
            self._g_queue.labels(model=name).set(float(m["queue_depth"]))
        primary = self.registry.primary_name()
        primary_gen = (self.registry.variant(primary).generation
                       if primary else None)
        return {
            "queue_depth": queue_depth,
            "generation": primary_gen,
            "draining": self.draining,
            "pipeline": {"in_flight": in_flight, "depth": depth_total},
            "brownout_level": self.brownout_level,
            "mux": {
                "registry": self.registry.snapshot(),
                "per_variant": per_variant,
                "costs": self.registry.costs(),
                "cost_sources": self.registry.cost_sources(),
                "ramp": (None if self.ramp is None
                         else self.ramp.snapshot()),
            },
        }

    def metrics_text(self) -> str:
        return get_registry().to_prometheus()

    # -- request handling -------------------------------------------------
    def _serve(self, kind: str, payload: Optional[dict],
               trace_id: Optional[str]) -> Tuple[int, dict]:
        payload = payload or {}
        # the routing key: sticky per user/session when the client sends
        # one; otherwise minted per request (weight-proportional split,
        # no stickiness to honor). "model" pins a variant outright —
        # probes and drills, not the normal path.
        pinned = payload.get("model")
        key = payload.get("key")
        if key is not None and not isinstance(key, str):
            return 400, {"status": "error",
                         "error": f"bad 'key': {key!r} (want a string)"}
        if pinned is not None:
            if not isinstance(pinned, str):
                return 400, {"status": "error",
                             "error": f"bad 'model': {pinned!r}"}
            try:
                variant = self.registry.variant(pinned)
            except KeyError:
                return 404, {"status": "error",
                             "error": f"unknown model {pinned!r}"}
            if variant.state != "resident":
                return 503, {"status": "overloaded", "model": pinned,
                             "error": f"model {pinned!r} is not resident"}
            name, batcher = pinned, self.registry.batcher_for(pinned)
        else:
            try:
                name, batcher = self.registry.route(
                    key if key is not None else uuid.uuid4().hex)
            except LookupError as exc:
                return 503, {"status": "overloaded", "error": str(exc)}
        if name in self._shed_set():
            # the per-model brownout: honest 503, counted per variant,
            # and fed into the variant's availability SLI (a brownout
            # IS an availability event for the model it silences)
            self._c_brownout_sheds.labels(model=name).inc()
            self._c_requests(model=name, kind=kind,
                             status="brownout_shed").inc()
            self.tracker_for(name).record(False)
            return 503, {
                "status": "overloaded", "model": name,
                "error": f"brownout: model {name!r} is shed until the "
                         f"fleet recovers (tier {self.brownout_level})"}
        engine = self.registry.engine_for(name)
        if engine is None or batcher is None:
            return 503, {"status": "overloaded", "model": name,
                         "error": f"model {name!r} was demoted mid-route"}
        if kind not in engine.kinds:
            return 404, {"status": "error", "model": name,
                         "error": f"unknown request kind {kind!r}"}
        data = payload.get("data")
        if data is None:
            return 400, {"status": "error", "error": "missing 'data'"}
        try:
            rows = np.asarray(data, dtype=np.float32)
        except (TypeError, ValueError) as exc:
            return 400, {"status": "error", "error": f"bad 'data': {exc}"}
        if rows.ndim == 1:
            rows = rows[None, :]
        width = engine.input_width(kind)
        if rows.ndim != 2 or rows.shape[0] < 1 or rows.shape[1] != width:
            return 400, {
                "status": "error",
                "error": f"{kind}: expected (n >= 1, {width}) rows, "
                         f"got {tuple(rows.shape)}"}
        timeout = payload.get("timeout")
        if timeout is not None:
            try:
                timeout = float(timeout)
            except (TypeError, ValueError):
                return 400, {"status": "error",
                             "error": f"bad 'timeout': {timeout!r}"}
        if TRACER.enabled:
            token = bind_trace_id(
                sanitize_trace_id(trace_id) or new_trace_id())
            try:
                with TRACER.span("mux.request", kind=kind, model=name,
                                 rows=int(rows.shape[0])):
                    result = batcher.submit(kind, rows, timeout=timeout)
            finally:
                unbind_trace_id(token)
        else:
            result = batcher.submit(kind, rows, timeout=timeout)
        self._c_requests(model=name, kind=kind, status=result.status).inc()
        self.tracker_for(name).record(
            result.ok, result.latency_s if result.ok else None)
        if result.ok:
            self._h_latency.labels(model=name).observe(result.latency_s)
        body = {"status": result.status, "model": name,
                "latency_ms": result.latency_s * 1e3}
        if result.ok:
            body["data"] = np.asarray(result.data).tolist()
        elif result.error:
            body["error"] = result.error
        return _STATUS_HTTP.get(result.status, 500), body

    def _mux_admin(self, path: str, payload: Optional[dict]
                   ) -> Tuple[int, dict]:
        payload = payload or {}
        if path == "/mux/weights":
            weights = payload.get("weights")
            if not isinstance(weights, dict) or not weights:
                return 400, {"status": "error",
                             "error": "need {'weights': {model: weight}}"}
            try:
                self.registry.set_weights(
                    {str(n): float(w) for n, w in weights.items()})
            except (KeyError, ValueError, TypeError) as exc:
                return 400, {"status": "error",
                             "error": f"{type(exc).__name__}: {exc}"}
            return 200, {"status": "ok",
                         "shares": self.registry.splitter.shares()}
        if path == "/mux/brownout":
            level = payload.get("level")
            if not isinstance(level, int):
                return 400, {"status": "error",
                             "error": f"need an integer 'level', "
                                      f"got {level!r}"}
            return 200, {"status": "ok",
                         "level": self.set_brownout(level)}
        if path == "/mux/ramp":
            candidate = payload.get("candidate")
            if not isinstance(candidate, str):
                return 400, {"status": "error",
                             "error": "need {'candidate': model}"}
            if candidate not in self.registry.names():
                return 404, {"status": "error",
                             "error": f"unknown model {candidate!r}"}
            try:
                ramp = self.start_ramp(
                    candidate,
                    stages=payload.get("stages"),
                    hold_ticks=int(payload.get("hold_ticks", 2)))
            except (RuntimeError, ValueError) as exc:
                return 409, {"status": "error", "error": str(exc)}
            return 200, {"status": "ok", "ramp": ramp.snapshot()}
        return 404, {"status": "error", "error": f"no route POST {path}"}

    def handle(self, method: str, path: str, payload: Optional[dict] = None,
               trace_id: Optional[str] = None) -> Tuple[int, dict]:
        """The single routing table (the same contract the single-model
        ``InferenceService.handle`` exposes, so ``make_server`` fronts
        either)."""
        path, _, query = path.partition("?")
        params = parse_qs(query) if query else {}
        if method == "GET" and path == "/healthz":
            return 200, self.healthz()
        if method == "GET" and path == "/metrics":
            if params.get("scope", [""])[0] == "registry":
                return 200, get_registry().snapshot(include_samples=True)
            return 200, self.metrics()
        if method == "GET" and path == "/mux/status":
            return 200, self.healthz()
        if method == "GET" and path == "/alerts":
            if self.alerts is None:
                return 404, {"status": "error",
                             "error": "no alert plane attached"}
            return 200, self.alerts.snapshot()
        if method == "GET" and path == "/debug/spans":
            return 200, TRACER.chrome_trace(
                {"source": "gan_deeplearning4j_tpu.serving.mux"})
        if method == "POST" and path == "/admin/drain":
            self.draining = params.get("off", ["0"])[0] in ("0", "", "false")
            return 200, {"status": "ok", "draining": self.draining}
        if method == "POST" and path.startswith("/mux/"):
            return self._mux_admin(path, payload)
        if method == "POST" and path.startswith("/v1/"):
            return self._serve(path[len("/v1/"):], payload, trace_id)
        return 404, {"status": "error", "error": f"no route {method} {path}"}

    def close(self) -> None:
        self._loop_stop.set()
        t = self._loop_thread
        if t is not None:
            t.join(timeout=5.0)
        self.registry.close()
