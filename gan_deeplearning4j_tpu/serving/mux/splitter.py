"""WeightedSplitter — deterministic hash-based traffic assignment.

The multiplexing plane (docs/MULTIPLEX.md) serves N model variants behind
ONE request surface; this module decides, per request key, which variant
answers. Three properties make that decision an infrastructure primitive
rather than a load balancer heuristic:

- **deterministic** — the assignment is a pure function of (key, variant
  names, weights) computed from a *seeded stdlib hash* (sha256), never
  Python's salted ``hash()`` and never process state: the same key routes
  to the same variant across router restarts, across processes, and
  across replicas, as long as the weights match. Sticky assignment is
  what makes a canary ramp meaningful — one user's traffic does not
  flap between the incumbent and the candidate on every request.
- **exactly weight-proportional** — assignment is weighted rendezvous
  (highest-random-weight) hashing: each variant scores
  ``weight / Exp(1)`` where the exponential draw is derived from
  ``sha256(key, variant)``, and the highest score wins. The winner
  distribution is *exactly* ``w_i / Σw`` (the max of competing
  scaled exponentials — argmin of ``Exp(w_i)`` — lands on ``i`` with
  probability proportional to its rate), so a 1% stage of the ramp
  controller really is 1% in expectation, not "roughly the smallest
  bucket".
- **minimal reassignment under live weight updates** — when one
  variant's weight is raised, keys only ever move *to* that variant
  (its scores grew; everyone else's are untouched), and the expected
  moved fraction is exactly the variant's share delta. Lowering a
  weight moves only that variant's keys away. A ramp step therefore
  disturbs precisely the traffic it admits — no global reshuffle, the
  property the determinism tests pin.

Weights are free-scale (only ratios matter); weight 0 removes a variant
from assignment without forgetting it. Thread-safe: weight reads/updates
take one lock; the hash math itself is pure.
"""

from __future__ import annotations

import hashlib
import math
import threading
from typing import Dict, Iterable, Mapping, Optional, Tuple

#: 2**64 as a float divisor — maps a 64-bit digest prefix into (0, 1)
_SCALE = float(1 << 64)


def _uniform(key: str, variant: str) -> float:
    """A deterministic uniform draw in (0, 1) for (key, variant), from
    sha256 — NOT ``hash()``, which is salted per process and would
    reassign every key on every restart."""
    digest = hashlib.sha256(
        f"{key}\x00{variant}".encode("utf-8", "surrogatepass")).digest()
    # +1 keeps the draw strictly positive so log() below is finite
    return (int.from_bytes(digest[:8], "big") + 1) / (_SCALE + 2.0)


class WeightedSplitter:
    """Weighted rendezvous assignment over named variants.

    ``assign(key)`` returns the variant whose score
    ``-weight / ln(u(key, variant))`` is highest — equivalently the
    argmin of per-variant exponentials with rate ``weight``, which is
    weight-proportional and minimally disruptive under weight changes
    (module docstring). Raises :class:`LookupError` when no variant
    carries positive weight."""

    def __init__(self, weights: Optional[Mapping[str, float]] = None):
        self._lock = threading.Lock()
        self._weights: Dict[str, float] = {}
        if weights:
            self.set_weights(weights)

    # -- weight management ----------------------------------------------
    @staticmethod
    def _validate(name: str, weight: float) -> float:
        weight = float(weight)
        if not math.isfinite(weight) or weight < 0.0:
            raise ValueError(
                f"weight for {name!r} must be finite and >= 0, "
                f"got {weight!r}")
        return weight

    def set_weight(self, name: str, weight: float) -> None:
        """Set (or add) one variant's weight live; 0 stops new
        assignments without removing the variant."""
        weight = self._validate(name, weight)
        with self._lock:
            self._weights[str(name)] = weight

    def set_weights(self, weights: Mapping[str, float]) -> None:
        """Replace-or-update several weights atomically — one lock, so a
        ramp step (candidate up, incumbent down) is a single transition
        no concurrent ``assign`` can observe half-applied."""
        validated = {str(n): self._validate(n, w)
                     for n, w in weights.items()}
        with self._lock:
            self._weights.update(validated)

    def remove(self, name: str) -> None:
        with self._lock:
            self._weights.pop(name, None)

    def weights(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._weights)

    # -- assignment -------------------------------------------------------
    def assign(self, key: str, among: Optional[Iterable[str]] = None) -> str:
        """The variant for ``key``. ``among`` restricts candidates (the
        mux service passes the currently *resident* names so a cold
        variant's share falls back to the survivors by the same
        rendezvous order instead of erroring)."""
        with self._lock:
            if among is None:
                candidates: Tuple[Tuple[str, float], ...] = tuple(
                    (n, w) for n, w in self._weights.items() if w > 0.0)
            else:
                candidates = tuple(
                    (n, self._weights.get(n, 0.0)) for n in among
                    if self._weights.get(n, 0.0) > 0.0)
        if not candidates:
            raise LookupError("no variant carries positive weight")
        key = str(key)
        best_name, best_score = None, -math.inf
        # sorted: ties (same weight AND same digest — practically never)
        # resolve identically on every process
        for name, weight in sorted(candidates):
            u = _uniform(key, name)
            score = -weight / math.log(u)
            if score > best_score:
                best_name, best_score = name, score
        return best_name

    def shares(self) -> Dict[str, float]:
        """Each positively-weighted variant's expected traffic fraction
        (``w / Σw``) — the number dashboards and the drill compare
        observed splits against."""
        with self._lock:
            live = {n: w for n, w in self._weights.items() if w > 0.0}
        total = sum(live.values())
        return {n: w / total for n, w in live.items()} if total else {}

    def snapshot(self) -> dict:
        with self._lock:
            weights = dict(self._weights)
        total = sum(w for w in weights.values() if w > 0.0)
        return {
            "weights": weights,
            "shares": {n: (w / total if total and w > 0.0 else 0.0)
                       for n, w in weights.items()},
        }
