"""MuxRegistry — N named serving variants behind one residency budget.

The single-model serving process keeps exactly one :class:`ServingEngine`
alive and hot-swaps it on reload (docs/DEPLOY.md). The multiplexing plane
generalizes that singleton into a *registry* of named variants — distinct
store generations, or cheap (bf16-cast) siblings of one generation — each
wrapped in its own engine + micro-batcher, with three properties the
singleton never needed (docs/MULTIPLEX.md):

- **shared staging residency** — every resident engine stages its
  flushes through ONE :class:`SharedStagingPool` (buffers are keyed by
  ``(bucket, width)`` — model-agnostic pinned bytes), so N resident
  variants cost ~one engine's worth of staging instead of N: residency
  scales sub-linearly, which is the whole economic argument for keeping
  more variants HBM-resident (the μ-cuDNN precision/residency trade,
  PAPERS.md).
- **a residency budget with least-weighted eviction** — ``budget``
  bounds how many engines stay resident. Admitting one more (adopt or
  re-warm) demotes the least-weighted demotable variant back to its
  *cold manifest* (bundle path + metadata; engine, batcher, and AOT
  executables dropped). A cold variant re-warms through the same build
  path the reload plane uses (``from_bundle`` against the registry's
  ladder, sync AOT warmup, ``export_gauge=False``) when its weight
  returns.
- **one lock for every cross-variant access** — ``lock`` guards the
  variant table. Every read of another generation's engine/batcher goes
  through it (or through the accessors here, which take it); jaxlint
  JG022 polices direct ``.variants``-table access outside the lock, the
  multi-generation analogue of the JG016 swap-seam rule.

Routing weights live in the registry's :class:`~.splitter.WeightedSplitter`
(so eviction can ask "least-weighted" of the same numbers requests are
split by); ``route(key)`` resolves a request key to a (name, batcher)
pair among *resident, positively-weighted* variants, falling back past
cold ones (counted — a fallback is a residency-budget miss, the signal an
operator sizes the budget with).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from gan_deeplearning4j_tpu.serving.batcher import MicroBatcher
from gan_deeplearning4j_tpu.serving.engine import (
    DEFAULT_BUCKETS,
    _StagingBuf,
)
from gan_deeplearning4j_tpu.serving.ladder import (
    SizeHistogram,
    manifest_histogram,
    manifest_ladder,
)
from gan_deeplearning4j_tpu.serving.mux.splitter import WeightedSplitter
from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import TRACER

logger = logging.getLogger(__name__)

#: buffers kept per (bucket, width) key in the shared pool — the same
#: depth a single engine keeps privately; shared, it serves EVERY
#: resident variant (that is the sub-linear part)
_SHARED_POOL_LIMIT = 4

#: variant lifecycle states (mux_variant_state gauge exports the index)
STATES = ("cold", "warming", "resident", "failed")
_STATE_CODE = {name: i for i, name in enumerate(STATES)}


class SharedStagingPool:
    """One pinned-staging-buffer pool shared by every resident engine.

    Buffers are plain ``(bucket, width)`` float32 arrays with a
    high-water zero tail (:class:`~..engine._StagingBuf`) — nothing about
    them is model-specific, so variants of any generation can recycle
    each other's. ``checkout``/``checkin`` mirror the engine's private
    pool API; the pool never blocks (an empty pool allocates)."""

    def __init__(self, per_key_limit: int = _SHARED_POOL_LIMIT):
        if per_key_limit < 1:
            raise ValueError("per_key_limit must be >= 1")
        self._limit = per_key_limit
        self._lock = threading.Lock()
        self._pools: Dict[Tuple[int, int], List[_StagingBuf]] = {}
        self._allocated = 0

    def checkout(self, bucket: int, width: int) -> _StagingBuf:
        key = (int(bucket), int(width))
        with self._lock:
            pool = self._pools.get(key)
            if pool:
                return pool.pop()
            self._allocated += 1
        return _StagingBuf(key[0], key[1])

    def checkin(self, buf: _StagingBuf) -> None:
        key = (buf.arr.shape[0], buf.arr.shape[1])
        with self._lock:
            pool = self._pools.setdefault(key, [])
            if len(pool) < self._limit:
                pool.append(buf)

    def stats(self) -> dict:
        with self._lock:
            pooled = sum(len(p) for p in self._pools.values())
            pooled_bytes = sum(
                b.arr.nbytes for p in self._pools.values() for b in p)
            return {
                "allocated_total": self._allocated,
                "pooled": pooled,
                "pooled_bytes": pooled_bytes,
                "keys": len(self._pools),
            }


class MuxVariant:
    """One named serving variant: a cold manifest always, an engine +
    batcher only while resident. Mutated ONLY under the registry lock.

    ``cost`` — the number eviction and brownout rank by — prefers the
    MEASURED scalar (a ``quant/cost.py`` block: residency-rent
    GiB·s/kilorow profiled on the live ladder) and falls back to the
    operator-declared bootstrap value until one lands. ``cost_source``
    names which of the two is live (``measured``/``declared``) so
    dashboards can tell economics from guesswork."""

    __slots__ = ("name", "bundle_path", "declared_cost", "measured",
                 "generation", "state", "engine", "batcher", "last_error",
                 "added_at", "warmed_at", "histogram")

    def __init__(self, name: str, *, bundle_path: Optional[str],
                 cost: float, generation):
        self.name = name
        self.bundle_path = bundle_path
        self.declared_cost = float(cost)
        #: measured cost block (quant/cost.py schema) or None (bootstrap)
        self.measured: Optional[dict] = None
        self.generation = generation
        self.state = "cold"
        self.engine = None
        self.batcher = None
        self.last_error: Optional[str] = None
        self.added_at = time.time()
        self.warmed_at: Optional[float] = None
        # per-variant flush-size histogram (serving/ladder.py): owned
        # by the VARIANT, not the batcher, so learned traffic shape
        # survives demote/re-warm cycles; each residency's batcher
        # records straight into it
        self.histogram = SizeHistogram()

    @property
    def cost(self) -> float:
        if self.measured is not None:
            return float(self.measured["scalar"])
        return self.declared_cost

    @property
    def cost_source(self) -> str:
        return "measured" if self.measured is not None else "declared"

    def set_measured(self, block: Optional[dict]) -> None:
        """Adopt (or clear, with None) a measured cost block. The block
        must carry a positive ``scalar`` — a zero/negative measurement
        would silently game shed ordering."""
        if block is not None:
            scalar = block.get("scalar")
            if not isinstance(scalar, (int, float)) or scalar <= 0:
                raise ValueError(
                    f"measured cost block for {self.name!r} needs a "
                    f"positive 'scalar', got {scalar!r}")
        self.measured = dict(block) if block is not None else None

    def snapshot(self, weight: float) -> dict:
        engine = self.engine
        measured = self.measured
        return {
            "name": self.name,
            "state": self.state,
            "cost": self.cost,
            "cost_source": self.cost_source,
            "declared_cost": self.declared_cost,
            "measured_cost": (
                None if measured is None else float(measured["scalar"])),
            "resident_param_bytes": (
                None if measured is None
                else measured.get("resident_param_bytes")),
            "precision": (
                None if measured is None else measured.get("precision")),
            "weight": weight,
            "generation": self.generation,
            "bundle_path": self.bundle_path,
            "resident": self.state == "resident",
            "warm": bool(engine is not None and engine.warmed),
            # the ladder this residency compiled (None while cold) and
            # how much traffic shape the variant has accumulated — the
            # learned-ladder observability pair (serving/ladder.py)
            "buckets": (None if engine is None
                        else list(getattr(engine, "buckets", ()) or ())
                        or None),
            "histogram_rows": self.histogram.total(),
            "last_error": self.last_error,
        }


class MuxRegistry:
    """The variant table + splitter + residency policy (module docstring).

    ``build`` is injectable for tests: ``(variant) -> engine``; the
    default loads ``ServingEngine.from_bundle`` against the registry's
    bucket ladder and replica count with the shared staging pool
    attached. ``batcher_kwargs`` applies to every variant's
    :class:`MicroBatcher` (``max_batch`` defaults to the ladder top)."""

    def __init__(self, *, buckets: Sequence[int] = DEFAULT_BUCKETS,
                 replicas: int = 1, budget: int = 2,
                 batcher_kwargs: Optional[dict] = None,
                 build: Optional[Callable] = None,
                 staging_pool: Optional[SharedStagingPool] = None):
        if budget < 1:
            raise ValueError("residency budget must be >= 1")
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.replicas = int(replicas)
        self.budget = int(budget)
        self.pool = staging_pool or SharedStagingPool()
        self.splitter = WeightedSplitter()
        self._build = build or self._default_build
        self._batcher_kwargs = dict(batcher_kwargs or {})
        # THE cross-generation lock (jaxlint JG022): every access to the
        # variant table — and through it to another generation's engine
        # or batcher — holds it. RLock: accessors compose (snapshot()
        # calls primary_name() and such under one acquisition).
        self.lock = threading.RLock()
        self._variants: Dict[str, MuxVariant] = {}
        self.events: List[dict] = []
        registry = get_registry()
        self._g_resident = registry.gauge(
            "mux_variants_resident",
            "engines currently resident in the mux registry")
        self._g_weight = registry.gauge(
            "mux_variant_weight",
            "live routing weight per variant (0 = no new traffic)",
            labelnames=("model",))
        self._g_state = registry.gauge(
            "mux_variant_state",
            "variant lifecycle: 0=cold 1=warming 2=resident 3=failed",
            labelnames=("model",))
        self._c_evictions = registry.counter(
            "mux_evictions_total",
            "variants demoted from resident engines to cold manifests by "
            "the residency budget", labelnames=("model",))
        self._c_warmups = registry.counter(
            "mux_warmups_total",
            "engine builds (adopt or cold re-warm) per variant",
            labelnames=("model",))
        self._c_fallbacks = registry.counter(
            "mux_route_fallbacks_total",
            "requests whose assigned variant was not resident and fell "
            "back to the resident pool (residency-budget misses)")
        self._g_cost = registry.gauge(
            "mux_variant_cost",
            "the cost eviction/brownout rank by (measured scalar when "
            "one landed, declared bootstrap otherwise)",
            labelnames=("model",))
        self._g_cost_source = registry.gauge(
            "mux_variant_cost_source",
            "1 = cost is a live-ladder measurement (quant/cost.py), "
            "0 = operator-declared bootstrap", labelnames=("model",))
        self._g_resident_bytes = registry.gauge(
            "mux_variant_resident_param_bytes",
            "measured device bytes one replica of the variant's params "
            "pins (0 until measured)", labelnames=("model",))

    # -- builds (the PR 7 reloader path, shared-pool edition) -------------
    def build_engine(self, bundle_path: str,
                     fallback_buckets: Optional[Sequence[int]] = None):
        """THE build recipe for this registry's engines — the variant's
        own LEARNED ladder when its bundle manifest carries one
        (serving/ladder.py; each variant's traffic shapes its own
        buckets), else ``fallback_buckets`` (the reload plane passes a
        ladder solved from the incumbent's histogram), else the registry
        default; replica count and the shared staging pool always. The
        registry-mode reload plane builds its candidates through this
        too, so adopted and re-warmed engines can never diverge in
        config."""
        from gan_deeplearning4j_tpu.serving.engine import ServingEngine

        return ServingEngine.from_bundle(
            bundle_path,
            buckets=(manifest_ladder(bundle_path) or fallback_buckets
                     or self.buckets),
            replicas=self.replicas,
            export_gauge=False,
            staging_pool=self.pool,
        )

    def _default_build(self, variant: MuxVariant):
        if variant.bundle_path is None:
            raise ValueError(
                f"variant {variant.name!r} has no bundle manifest to "
                f"build from")
        return self.build_engine(variant.bundle_path)

    def _make_batcher(self, engine,
                      variant: Optional[MuxVariant] = None) -> MicroBatcher:
        kwargs = dict(self._batcher_kwargs)
        # the ENGINE's ladder top, not the registry default: a variant
        # warmed on its own learned ladder must batch to ITS top bucket
        # (registry default when the engine carries no ladder)
        ladder = getattr(engine, "buckets", None) or self.buckets
        kwargs.setdefault("max_batch", ladder[-1])
        if variant is not None:
            kwargs.setdefault("size_histogram", variant.histogram)
        return MicroBatcher(engine=engine, **kwargs)

    # -- variant management ----------------------------------------------
    def add(self, name: str, *, bundle_path: Optional[str] = None,
            engine=None, cost: float = 1.0, weight: float = 0.0,
            generation=None) -> MuxVariant:
        """Register a variant. With ``engine`` (already built + warmed —
        the adopt path) it becomes resident immediately; with only a
        ``bundle_path`` it stays a cold manifest until its weight asks
        for residency. ``cost`` is the DECLARED relative serve cost (bf16
        sibling < fp32 original) — a bootstrap default: when the bundle's
        manifest carries a measured ``cost`` block (quant/cost.py), the
        measurement is adopted immediately and eviction + brownout rank
        by it instead — highest cost sheds first (docs/MULTIPLEX.md,
        docs/QUANT.md)."""
        if bundle_path is None and engine is None:
            raise ValueError("a variant needs a bundle_path or an engine")
        if cost <= 0:
            raise ValueError("cost must be > 0")
        name = str(name)
        if generation is None and engine is not None:
            generation = engine.generation
        variant = MuxVariant(name, bundle_path=bundle_path, cost=cost,
                             generation=generation)
        if bundle_path is not None:
            from gan_deeplearning4j_tpu.quant.cost import manifest_cost

            block = manifest_cost(bundle_path)
            if block is not None:
                variant.set_measured(block)
            # boot the variant's live histogram from the traffic shape
            # persisted with its bundle (serving/ladder.py), so learning
            # compounds across generations instead of restarting cold
            persisted = manifest_histogram(bundle_path)
            if persisted:
                variant.histogram.merge(persisted)
        with self.lock:
            if name in self._variants:
                raise ValueError(f"variant {name!r} already registered")
            self._variants[name] = variant
            if engine is not None:
                self._attach_locked(variant, engine)
        self.splitter.set_weight(name, weight)
        self._g_weight.labels(model=name).set(float(weight))
        self._export_cost_gauges(variant)
        if engine is not None:
            self._enforce_budget(protect=name)
        elif weight > 0.0:
            self.ensure_resident(name)
        return variant

    def adopt(self, name: str, engine, *, bundle_path: Optional[str] = None,
              cost: float = 1.0, weight: float = 0.0,
              generation=None) -> MuxVariant:
        """The reload plane's entry point (docs/DEPLOY.md): a newly
        warmed candidate engine joins the registry as a variant —
        typically at weight 0, ready for a ramp — instead of replacing a
        singleton. The residency budget applies immediately. The
        incumbent primary's flush-size histogram is folded into the
        newcomer's (on top of anything its bundle manifest persisted),
        so the generation that will inherit the traffic also inherits
        its learned shape (ISSUE 19 carry-forward)."""
        incumbent = self.primary_name()
        variant = self.add(name, bundle_path=bundle_path, engine=engine,
                           cost=cost, weight=weight, generation=generation)
        if incumbent is not None and incumbent != name:
            with self.lock:
                prior = self._variants.get(incumbent)
                seed = prior.histogram.snapshot() if prior else None
            if seed:
                variant.histogram.merge(seed)
        with self.lock:
            self.events.append({"event": "adopt", "variant": name,
                                "generation": variant.generation})
        return variant

    def remove(self, name: str) -> None:
        """Drop a variant entirely (demoting it first when resident)."""
        self.demote(name)
        with self.lock:
            self._variants.pop(name, None)
        self.splitter.remove(name)

    def _attach_locked(self, variant: MuxVariant, engine) -> None:
        variant.engine = engine
        variant.batcher = self._make_batcher(engine, variant)
        variant.state = "resident"
        variant.warmed_at = time.time()
        variant.last_error = None
        if variant.generation is None:
            variant.generation = engine.generation
        self._g_state.labels(model=variant.name).set(
            _STATE_CODE["resident"])
        self._g_resident.set(
            sum(1 for v in self._variants.values()
                if v.state == "resident"))

    # -- residency --------------------------------------------------------
    def ensure_resident(self, name: str) -> MuxVariant:
        """Re-warm a cold variant through the reloader-style build path:
        engine from the cold manifest against the registry ladder +
        shared pool, sync AOT warmup, then attach. The (multi-second)
        build runs OUTSIDE the lock — routing to other variants never
        stalls behind a warmup."""
        with self.lock:
            variant = self._variants[name]
            if variant.state == "resident":
                return variant
            if variant.state == "warming":
                raise RuntimeError(f"variant {name!r} is already warming")
            variant.state = "warming"
        self._g_state.labels(model=name).set(_STATE_CODE["warming"])
        try:
            with TRACER.span("mux.warm", variant=name):
                engine = self._build(variant)
                engine.warmup()
            self._c_warmups.labels(model=name).inc()
        except Exception as exc:
            with self.lock:
                variant.state = "failed"
                variant.last_error = f"{type(exc).__name__}: {exc}"
            self._g_state.labels(model=name).set(_STATE_CODE["failed"])
            raise
        with self.lock:
            self._attach_locked(variant, engine)
            self.events.append({"event": "warm", "variant": name,
                                "generation": variant.generation})
        self._enforce_budget(protect=name)
        return variant

    def demote(self, name: str) -> bool:
        """Resident → cold manifest: detach engine + batcher under the
        lock, then drain/close the batcher and drop the engine outside
        it (in-flight requests finish on the detached pair; new route()
        calls no longer see the variant). False when not resident."""
        with self.lock:
            variant = self._variants.get(name)
            if variant is None or variant.state != "resident":
                return False
            batcher, engine = variant.batcher, variant.engine
            variant.batcher = None
            variant.engine = None
            variant.state = "cold"
            self.events.append({"event": "demote", "variant": name,
                                "generation": variant.generation})
            self._g_resident.set(
                sum(1 for v in self._variants.values()
                    if v.state == "resident"))
        self._g_state.labels(model=name).set(_STATE_CODE["cold"])
        if batcher is not None:
            batcher.close(drain=True)
        del engine  # AOT executables + device params released with it
        return True

    def _enforce_budget(self, protect: Optional[str] = None) -> None:
        """Demote least-weighted demotable residents until the count fits
        the budget. ``protect`` exempts the variant just admitted (the
        newcomer must not evict itself). A variant with no cold manifest
        (engine-only, nothing to re-warm from) is never demoted."""
        while True:
            weights = self.splitter.weights()
            with self.lock:
                residents = [v for v in self._variants.values()
                             if v.state == "resident"]
                if len(residents) <= self.budget:
                    return
                demotable = [
                    v for v in residents
                    if v.bundle_path is not None and v.name != protect]
                if not demotable:
                    return  # over budget but nothing safely demotable
                victim = min(
                    demotable,
                    key=lambda v: (weights.get(v.name, 0.0), -v.cost,
                                   v.name))
                victim_name = victim.name
            self._c_evictions.labels(model=victim_name).inc()
            self.demote(victim_name)

    # -- measured cost ------------------------------------------------------
    def _export_cost_gauges(self, variant: MuxVariant) -> None:
        measured = variant.measured
        self._g_cost.labels(model=variant.name).set(variant.cost)
        self._g_cost_source.labels(model=variant.name).set(
            1.0 if measured is not None else 0.0)
        self._g_resident_bytes.labels(model=variant.name).set(
            float(measured.get("resident_param_bytes") or 0)
            if measured is not None else 0.0)

    def set_measured_cost(self, name: str, block: dict) -> None:
        """Land a live-ladder measurement (quant/cost.py block) on a
        registered variant: ``cost`` flips from the declared bootstrap to
        the measured scalar, and every ranking that reads ``costs()`` —
        residency eviction, brownout shed order — follows on its next
        decision. Recorded in the event log (drills assert on it)."""
        with self.lock:
            variant = self._variants[name]
            variant.set_measured(block)
            self.events.append({
                "event": "cost_measured", "variant": name,
                "scalar": variant.cost,
                "resident_param_bytes": block.get("resident_param_bytes"),
            })
        self._export_cost_gauges(variant)

    # -- weights ----------------------------------------------------------
    def set_weight(self, name: str, weight: float,
                   warm: bool = True) -> None:
        """Live weight update. Raising a cold variant's weight above 0
        re-warms it first (``warm=False`` skips that — the caller will
        warm explicitly), so traffic is never assigned to a variant that
        cannot serve it without a fallback."""
        with self.lock:
            variant = self._variants[name]
            state = variant.state
        if weight > 0.0 and state == "cold" and warm:
            self.ensure_resident(name)
        self.splitter.set_weight(name, weight)
        self._g_weight.labels(model=name).set(float(weight))

    def set_weights(self, weights: Dict[str, float],
                    warm: bool = True) -> None:
        """Atomic multi-variant weight transition (one splitter lock —
        a ramp step is never observed half-applied). The weights land
        FIRST, then any cold variant gaining weight is re-warmed
        best-effort: a ramp rollback must restore the incumbents'
        traffic shares immediately even when one of them was
        budget-evicted mid-ramp and its multi-second re-warm (or a
        failing one) would otherwise delay — or worse, skip — the
        restore. Until the warm lands, that variant's keys take the
        counted fallback path (``mux_route_fallbacks_total``)."""
        self.splitter.set_weights(weights)
        for name, weight in weights.items():
            self._g_weight.labels(model=name).set(float(weight))
        if not warm:
            return
        with self.lock:
            cold = [n for n, w in weights.items()
                    if w > 0.0 and n in self._variants
                    and self._variants[n].state == "cold"]
        for name in cold:
            try:
                self.ensure_resident(name)
            except Exception:
                # the variant stays failed/cold and its traffic falls
                # back to the resident pool — degraded but serving,
                # never a lost weight transition
                logger.exception("re-warm of weighted variant %r failed",
                                 name)

    # -- routing ----------------------------------------------------------
    def route(self, key: str) -> Tuple[str, MicroBatcher]:
        """Resolve a request key to (variant name, its batcher) among
        resident, positively-weighted variants. When the key's
        *unrestricted* assignment names a non-resident variant, the
        request falls back to the resident pool by the same rendezvous
        order and the miss is counted (``mux_route_fallbacks_total``)."""
        weights = self.splitter.weights()
        with self.lock:
            resident = [n for n, v in self._variants.items()
                        if v.state == "resident"
                        and weights.get(n, 0.0) > 0.0]
            if not resident:
                raise LookupError(
                    "no resident variant carries positive weight")
            name = self.splitter.assign(key, among=resident)
            if any(w > 0.0 and n not in resident
                   for n, w in weights.items()):
                if self.splitter.assign(key) != name:
                    self._c_fallbacks.inc()
            return name, self._variants[name].batcher

    # -- accessors (all take the lock — the JG022-clean surface) ----------
    def names(self) -> List[str]:
        with self.lock:
            return list(self._variants)

    def resident_names(self) -> List[str]:
        with self.lock:
            return [n for n, v in self._variants.items()
                    if v.state == "resident"]

    def engine_for(self, name: str):
        with self.lock:
            return self._variants[name].engine

    def batcher_for(self, name: str) -> Optional[MicroBatcher]:
        with self.lock:
            return self._variants[name].batcher

    def variant(self, name: str) -> MuxVariant:
        with self.lock:
            return self._variants[name]

    def generations(self) -> Dict[str, object]:
        with self.lock:
            return {n: v.generation for n, v in self._variants.items()}

    def max_generation(self) -> Optional[int]:
        """The newest store generation any variant carries — what the
        registry-mode reload watcher polls against (docs/DEPLOY.md)."""
        with self.lock:
            gens = [v.generation for v in self._variants.values()
                    if isinstance(v.generation, int)]
        return max(gens) if gens else None

    def primary_name(self) -> Optional[str]:
        """The highest-weighted resident variant — the reload plane's
        incumbent for compatibility checks and canary probes."""
        weights = self.splitter.weights()
        with self.lock:
            residents = [n for n, v in self._variants.items()
                         if v.state == "resident"]
        if not residents:
            return None
        return max(residents, key=lambda n: (weights.get(n, 0.0), n))

    def reference_engine(self):
        name = self.primary_name()
        return None if name is None else self.engine_for(name)

    def costs(self) -> Dict[str, float]:
        with self.lock:
            return {n: v.cost for n, v in self._variants.items()}

    def cost_sources(self) -> Dict[str, str]:
        """Per-variant provenance of the ranking number —
        ``measured`` (live-ladder block) or ``declared`` (bootstrap)."""
        with self.lock:
            return {n: v.cost_source for n, v in self._variants.items()}

    def snapshot(self) -> dict:
        weights = self.splitter.weights()
        with self.lock:
            variants = {n: v.snapshot(weights.get(n, 0.0))
                        for n, v in self._variants.items()}
            resident = sum(1 for v in self._variants.values()
                           if v.state == "resident")
        return {
            "variants": variants,
            "resident": resident,
            "budget": self.budget,
            "buckets": list(self.buckets),
            "replicas": self.replicas,
            "shares": self.splitter.shares(),
            "staging_pool": self.pool.stats(),
        }

    def close(self) -> None:
        """Demote everything (drains every batcher) — shutdown path."""
        for name in self.resident_names():
            self.demote(name)
