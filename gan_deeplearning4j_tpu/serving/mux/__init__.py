"""serving/mux — the multi-model multiplexing plane (docs/MULTIPLEX.md).

Serves N model variants — distinct store generations, or cheap bf16
siblings of one generation — behind ONE request surface:

- :mod:`.splitter` — deterministic weighted-rendezvous traffic
  assignment per request key: sticky across restarts, exactly
  weight-proportional, minimal reassignment under live weight updates;
- :mod:`.registry` — the variant table: each variant wraps a
  :class:`~..engine.ServingEngine` + micro-batcher while *resident*,
  sharing one pinned staging pool across engines; a residency budget
  demotes least-weighted variants to cold manifests and re-warms them
  through the reload plane's build path when their weight returns;
- :mod:`.ramp` — the continuous canary ramp (1% → 10% → 50% → 100%)
  generalizing the deploy canary's single admission decision, with
  auto-rollback on the candidate's per-variant SLO burn;
- :mod:`.service` — the request surface (duck-types the single-model
  ``InferenceService`` handler contract, so ``serving.make_server``
  fronts it) with per-model metric labels, per-variant SLO trackers,
  and per-model brownout tiering: under overload the most expensive
  variant's traffic sheds first, the cheapest's last.
"""

from gan_deeplearning4j_tpu.serving.mux.ramp import (
    RampController,
    health_from_tracker,
)
from gan_deeplearning4j_tpu.serving.mux.registry import (
    MuxRegistry,
    MuxVariant,
    SharedStagingPool,
)
from gan_deeplearning4j_tpu.serving.mux.service import (
    BrownoutController,
    MuxService,
)
from gan_deeplearning4j_tpu.serving.mux.splitter import WeightedSplitter

__all__ = [
    "BrownoutController",
    "MuxRegistry",
    "MuxService",
    "MuxVariant",
    "RampController",
    "SharedStagingPool",
    "WeightedSplitter",
    "health_from_tracker",
]
