"""resilience/ — fault-tolerant training for preemptible workers.

The ROADMAP's production north star assumes TPU workers that can vanish at
any step: preemption is a scheduling policy, not an accident. This package
makes a training run survivable:

- :mod:`.store` — a generation-ledgered checkpoint store: each generation
  is published temp+fsync+atomic-rename with a manifest of per-file
  content digests; reads re-verify the digests, quarantine corrupt
  generations (never serving them as "latest"), and retention GC keeps
  the newest K plus every N-th generation;
- :mod:`.supervisor` — runs ``GanExperiment`` in resumable segments:
  restores params + updater state + step counter from the newest valid
  generation, traps worker faults with bounded exponential backoff,
  honors SIGTERM preemption by checkpointing then exiting cleanly, and
  guarantees *bit-exact* resume (interrupted-and-resumed == uninterrupted
  at equal total steps);
- :mod:`.mesh` — the multi-worker coordinated checkpoint plane: N workers
  stage per-shard manifests into one shared staging dir and worker 0
  two-phase-commits the mesh generation (all-shards barrier → whole-mesh
  digest commit marker → atomic rename), with elastic reshard-on-restore
  (a generation written by M workers restores bit-exactly onto N);
- :mod:`.faults` — a deterministic, seeded fault-injection plane (raise /
  preempt / kill at step N, slow or failed checkpoint writes, byte
  corruption, mesh commit-window kills and straggler writers) that the
  drill and the tests drive;
- ``python -m gan_deeplearning4j_tpu.resilience`` — the supervised worker
  CLI ``scripts/resilience_drill.py`` launches, kills, and relaunches.

Architecture notes: docs/RESILIENCE.md.
"""

from gan_deeplearning4j_tpu.resilience.faults import (
    FaultInjector,
    FaultSchedule,
    FaultSpec,
    InjectedFault,
    corrupt_generation,
)
from gan_deeplearning4j_tpu.resilience.mesh import (
    MeshCoordinator,
    MeshProtocolError,
    MeshTimeout,
    mesh_digest,
)
from gan_deeplearning4j_tpu.resilience.store import (
    CheckpointStore,
    Generation,
    tree_digest,
)
from gan_deeplearning4j_tpu.resilience.supervisor import (
    RetryBudgetExceeded,
    SupervisorConfig,
    TrainingSupervisor,
    UnsupportedExperimentError,
)

__all__ = [
    "CheckpointStore",
    "Generation",
    "MeshCoordinator",
    "MeshProtocolError",
    "MeshTimeout",
    "mesh_digest",
    "tree_digest",
    "FaultInjector",
    "FaultSchedule",
    "FaultSpec",
    "InjectedFault",
    "corrupt_generation",
    "RetryBudgetExceeded",
    "SupervisorConfig",
    "TrainingSupervisor",
    "UnsupportedExperimentError",
]
