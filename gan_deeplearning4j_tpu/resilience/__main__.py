"""Supervised training worker CLI —
``python -m gan_deeplearning4j_tpu.resilience``.

One invocation = one supervisor lifetime. The process-level contract the
drill (and any orchestrator) relies on:

- exit 0   — run completed (``total_steps`` reached, final generation
             published);
- exit 75  — preempted (EX_TEMPFAIL: a checkpoint was published and the
             worker exited cleanly; relaunch to continue) — in mesh mode
             also any worker fault (``status: worker_fault``): in-process
             retries are disabled under a gang, so the relauncher
             restarts the whole mesh from the last coordinated
             generation;
- exit 70  — terminal (EX_SOFTWARE: retry budget exhausted — relaunching
             without intervention would fail the same way);
- exit 76  — gang abort (EX_PROTOCOL: a mesh barrier timed out — a peer
             worker is dead or wedged; relaunch the WHOLE mesh with a
             fresh ``--mesh-token``, never just this worker);
- killed by signal — a hard fault; the store still holds a consistent
             generation, so relaunching resumes from it.

Mesh mode (``--mesh-size N --mesh-worker K``): N invocations of this CLI
against ONE ``--store`` form a coordinated checkpoint gang — each worker
stages its shard, worker 0 two-phase-commits the generation
(docs/RESILIENCE.md, resilience/mesh.py). ``--mesh-token`` must be unique
per gang launch (the relauncher's job) so stale rounds from a dead gang
can never collide with live ones.

The run summary (status, steps, restore/publish timings, fault events) is
written as JSON to ``--summary`` and echoed to stdout.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gan_deeplearning4j_tpu.resilience",
        description="fault-tolerant supervised training worker",
    )
    p.add_argument("--config", required=True,
                   help="ExperimentConfig JSON file")
    p.add_argument("--store", required=True, help="checkpoint store root")
    p.add_argument("--data", required=True,
                   help="npz with 'features' and 'labels' arrays")
    p.add_argument("--total-steps", type=int, required=True)
    p.add_argument("--publish-every", type=int, default=10)
    p.add_argument("--serve-store", default=None, metavar="DIR",
                   help="also publish inference bundles (generator + "
                        "classifier, no updater) into this checkpoint "
                        "store on a cadence — what a live server's reload "
                        "plane watches (docs/DEPLOY.md)")
    p.add_argument("--serve-publish-every", type=int, default=0,
                   help="serving-bundle cadence in steps (0 = follow "
                        "--publish-every; needs --serve-store)")
    p.add_argument("--max-retries", type=int, default=3)
    p.add_argument("--backoff-base", type=float, default=0.5)
    p.add_argument("--backoff-max", type=float, default=30.0)
    p.add_argument("--keep-last", type=int, default=3)
    p.add_argument("--keep-every", type=int, default=0)
    p.add_argument("--mesh-size", type=int, default=1,
                   help="number of coordinated checkpoint workers sharing "
                        "--store (1 = single-writer, the default)")
    p.add_argument("--mesh-worker", type=int, default=0,
                   help="this worker's id in [0, --mesh-size); worker 0 "
                        "is the commit coordinator")
    p.add_argument("--mesh-token", default="r0",
                   help="gang-launch token — MUST be fresh per relaunch "
                        "so a dead gang's staging can never be mistaken "
                        "for a live round")
    p.add_argument("--mesh-timeout", type=float, default=60.0,
                   help="bound on every in-round mesh wait, seconds; "
                        "expiry = gang abort (exit 76)")
    p.add_argument("--mesh-boot-timeout", type=float, default=300.0,
                   help="bound on the gang's first rendezvous (restore "
                        "resolution), absorbing cold-start skew")
    p.add_argument("--fault-schedule", default=None,
                   help="FaultSchedule JSON file (docs/RESILIENCE.md)")
    p.add_argument("--summary", default=None,
                   help="write the run summary JSON here as well as stdout")
    p.add_argument("--telemetry", action="store_true",
                   help="enable span tracing (also honored via "
                        "GDT_TELEMETRY=trace); metrics are always on")
    p.add_argument("--span-trace", default=None, metavar="PATH",
                   help="dump the span tracer's Chrome trace JSON here on "
                        "exit (implies --telemetry)")
    p.add_argument("--trace-artifacts", default=None, metavar="DIR",
                   help="SIGUSR2 captures a 1s jax.profiler device trace "
                        "into this dir (default: $GDT_TRACE_DIR or "
                        "./artifacts/device_traces)")
    args = p.parse_args(argv)

    from gan_deeplearning4j_tpu.harness import ExperimentConfig
    from gan_deeplearning4j_tpu.resilience import (
        CheckpointStore,
        FaultInjector,
        FaultSchedule,
        MeshCoordinator,
        MeshTimeout,
        RetryBudgetExceeded,
        SupervisorConfig,
        TrainingSupervisor,
        UnsupportedExperimentError,
    )

    from gan_deeplearning4j_tpu.telemetry import device as _device
    from gan_deeplearning4j_tpu.telemetry.registry import get_registry
    from gan_deeplearning4j_tpu.telemetry.trace import TRACER, configure_from_env

    if args.telemetry or args.span_trace:
        TRACER.enable()
    else:
        configure_from_env()
    # SIGUSR2 → one bounded on-demand device capture; the supervisor's
    # SIGTERM preemption handler is untouched (different signal, different
    # contract)
    _device.install_signal_capture(
        args.trace_artifacts or _device.default_artifacts_dir())

    cfg = ExperimentConfig.from_json(args.config)
    with np.load(args.data) as npz:
        features, labels = npz["features"], npz["labels"]
    if not 0 <= args.mesh_worker < max(args.mesh_size, 1):
        raise SystemExit(f"--mesh-worker {args.mesh_worker} outside mesh "
                         f"of {args.mesh_size}")
    faults = None
    if args.fault_schedule:
        faults = FaultInjector(FaultSchedule.from_json(args.fault_schedule),
                               worker_id=args.mesh_worker)
    mesh = None
    store = None
    if args.mesh_size > 1:
        store = CheckpointStore(args.store, keep_last=args.keep_last,
                                keep_every=args.keep_every,
                                fault_injector=faults)
        mesh = MeshCoordinator(
            args.store, worker=args.mesh_worker, world_size=args.mesh_size,
            token=args.mesh_token, timeout_s=args.mesh_timeout,
            boot_timeout_s=args.mesh_boot_timeout, faults=faults,
        )
    sup = TrainingSupervisor(
        cfg,
        SupervisorConfig(
            total_steps=args.total_steps,
            publish_every=args.publish_every,
            max_retries=args.max_retries,
            backoff_base_s=args.backoff_base,
            backoff_max_s=args.backoff_max,
            keep_last=args.keep_last,
            keep_every=args.keep_every,
            serve_publish_every=args.serve_publish_every,
        ),
        features, labels,
        store=store,
        store_root=args.store,
        faults=faults,
        serve_store_root=args.serve_store,
        mesh=mesh,
    )
    sup.install_signal_handlers()

    def emit(summary: dict) -> None:
        # one definition for bench artifacts and live metrics: the summary
        # carries a registry snapshot, so the drill's BENCH record quotes
        # the same series a scraper would
        summary["telemetry"] = get_registry().snapshot()
        text = json.dumps(summary, indent=2, default=str)
        if args.summary:
            with open(args.summary, "w") as fh:
                fh.write(text + "\n")
        print(text)
        if args.span_trace:
            TRACER.dump(args.span_trace,
                        {"source": "gan_deeplearning4j_tpu.resilience"})

    try:
        summary = sup.run()
    except RetryBudgetExceeded as exc:
        emit({"status": "terminal", "error": str(exc),
              "events": sup.events})
        return 70  # EX_SOFTWARE
    except MeshTimeout as exc:
        emit({"status": "mesh_abort", "error": str(exc),
              "events": sup.events})
        return 76  # EX_PROTOCOL: relaunch the whole gang, fresh token
    except Exception as exc:
        if mesh is None or isinstance(exc, UnsupportedExperimentError):
            # single-writer faults are retried in-process by the
            # supervisor, and a terminal config error retries into the
            # same wall on any mesh — both deserve the loud traceback
            raise
        # mesh mode disables in-process retries (a retry would rejoin
        # barriers its peers are not at), so ANY worker fault surfaces
        # here; the remedy is the relauncher's — restart the whole gang
        # with a fresh token — which is exactly what 75 asks for
        emit({"status": "worker_fault",
              "error": f"{type(exc).__name__}: {exc}",
              "events": sup.events})
        return 75  # EX_TEMPFAIL
    emit(summary)
    return 0 if summary["status"] == "completed" else 75  # EX_TEMPFAIL


if __name__ == "__main__":
    sys.exit(main())
