"""Supervised training worker CLI —
``python -m gan_deeplearning4j_tpu.resilience``.

One invocation = one supervisor lifetime. The process-level contract the
drill (and any orchestrator) relies on:

- exit 0   — run completed (``total_steps`` reached, final generation
             published);
- exit 75  — preempted (EX_TEMPFAIL: a checkpoint was published and the
             worker exited cleanly; relaunch to continue);
- exit 70  — terminal (EX_SOFTWARE: retry budget exhausted — relaunching
             without intervention would fail the same way);
- killed by signal — a hard fault; the store still holds a consistent
             generation, so relaunching resumes from it.

The run summary (status, steps, restore/publish timings, fault events) is
written as JSON to ``--summary`` and echoed to stdout.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m gan_deeplearning4j_tpu.resilience",
        description="fault-tolerant supervised training worker",
    )
    p.add_argument("--config", required=True,
                   help="ExperimentConfig JSON file")
    p.add_argument("--store", required=True, help="checkpoint store root")
    p.add_argument("--data", required=True,
                   help="npz with 'features' and 'labels' arrays")
    p.add_argument("--total-steps", type=int, required=True)
    p.add_argument("--publish-every", type=int, default=10)
    p.add_argument("--serve-store", default=None, metavar="DIR",
                   help="also publish inference bundles (generator + "
                        "classifier, no updater) into this checkpoint "
                        "store on a cadence — what a live server's reload "
                        "plane watches (docs/DEPLOY.md)")
    p.add_argument("--serve-publish-every", type=int, default=0,
                   help="serving-bundle cadence in steps (0 = follow "
                        "--publish-every; needs --serve-store)")
    p.add_argument("--max-retries", type=int, default=3)
    p.add_argument("--backoff-base", type=float, default=0.5)
    p.add_argument("--backoff-max", type=float, default=30.0)
    p.add_argument("--keep-last", type=int, default=3)
    p.add_argument("--keep-every", type=int, default=0)
    p.add_argument("--fault-schedule", default=None,
                   help="FaultSchedule JSON file (docs/RESILIENCE.md)")
    p.add_argument("--summary", default=None,
                   help="write the run summary JSON here as well as stdout")
    p.add_argument("--telemetry", action="store_true",
                   help="enable span tracing (also honored via "
                        "GDT_TELEMETRY=trace); metrics are always on")
    p.add_argument("--span-trace", default=None, metavar="PATH",
                   help="dump the span tracer's Chrome trace JSON here on "
                        "exit (implies --telemetry)")
    p.add_argument("--trace-artifacts", default=None, metavar="DIR",
                   help="SIGUSR2 captures a 1s jax.profiler device trace "
                        "into this dir (default: $GDT_TRACE_DIR or "
                        "./artifacts/device_traces)")
    args = p.parse_args(argv)

    from gan_deeplearning4j_tpu.harness import ExperimentConfig
    from gan_deeplearning4j_tpu.resilience import (
        FaultInjector,
        FaultSchedule,
        RetryBudgetExceeded,
        SupervisorConfig,
        TrainingSupervisor,
    )

    from gan_deeplearning4j_tpu.telemetry import device as _device
    from gan_deeplearning4j_tpu.telemetry.registry import get_registry
    from gan_deeplearning4j_tpu.telemetry.trace import TRACER, configure_from_env

    if args.telemetry or args.span_trace:
        TRACER.enable()
    else:
        configure_from_env()
    # SIGUSR2 → one bounded on-demand device capture; the supervisor's
    # SIGTERM preemption handler is untouched (different signal, different
    # contract)
    _device.install_signal_capture(
        args.trace_artifacts or _device.default_artifacts_dir())

    cfg = ExperimentConfig.from_json(args.config)
    with np.load(args.data) as npz:
        features, labels = npz["features"], npz["labels"]
    faults = None
    if args.fault_schedule:
        faults = FaultInjector(FaultSchedule.from_json(args.fault_schedule))
    sup = TrainingSupervisor(
        cfg,
        SupervisorConfig(
            total_steps=args.total_steps,
            publish_every=args.publish_every,
            max_retries=args.max_retries,
            backoff_base_s=args.backoff_base,
            backoff_max_s=args.backoff_max,
            keep_last=args.keep_last,
            keep_every=args.keep_every,
            serve_publish_every=args.serve_publish_every,
        ),
        features, labels,
        store_root=args.store,
        faults=faults,
        serve_store_root=args.serve_store,
    )
    sup.install_signal_handlers()

    def emit(summary: dict) -> None:
        # one definition for bench artifacts and live metrics: the summary
        # carries a registry snapshot, so the drill's BENCH record quotes
        # the same series a scraper would
        summary["telemetry"] = get_registry().snapshot()
        text = json.dumps(summary, indent=2, default=str)
        if args.summary:
            with open(args.summary, "w") as fh:
                fh.write(text + "\n")
        print(text)
        if args.span_trace:
            TRACER.dump(args.span_trace,
                        {"source": "gan_deeplearning4j_tpu.resilience"})

    try:
        summary = sup.run()
    except RetryBudgetExceeded as exc:
        emit({"status": "terminal", "error": str(exc),
              "events": sup.events})
        return 70  # EX_SOFTWARE
    emit(summary)
    return 0 if summary["status"] == "completed" else 75  # EX_TEMPFAIL


if __name__ == "__main__":
    sys.exit(main())
