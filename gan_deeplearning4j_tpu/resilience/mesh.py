"""Mesh-scale checkpoint coordination — N workers, one generation.

PR 5's store is single-writer: one supervisor stages a full checkpoint and
an atomic rename publishes it. At mesh scale that design wastes N-1 copies
of every byte (each replica redundantly holds full state) and has no story
for a worker dying mid-publish. This module makes a *mesh generation* the
unit of durability: each of N workers stages only its **shard** of the
trained state, and worker 0 commits the whole generation with a two-phase
protocol whose publication point is still one atomic rename — so the
all-or-nothing property of the single-writer store survives the move to N
writers.

Coordination substrate: the shared store root itself (stdlib file
barriers). No sockets, no coordinator service — a worker that can write
its shard can also rendezvous, and the store's durability guarantees
(temp+fsync+rename for every marker) double as the barrier's. Layout:

```
<root>/
  .mesh/<token>/                     # barrier + decision files for one gang
    restore-0.json                   # worker 0's restore resolution
    <name>/w00001                    # barrier arrival markers
  .mesh-stage-<token>-gen-00000007/  # the shared staging dir for one round
    ROUND.json                       # worker 0's round announcement
    <prefix>_state_shard-0001-of-0002.zip   # worker 1's staged shard
    SHARD-00001.json                 # worker 1's phase-1 vote (shard manifest)
    MANIFEST.json                    # worker 0's commit marker (phase 2)
  generations/gen-00000007/          # the published mesh generation
```

The two-phase publish, step by step (``publish()``):

1. **round open** — worker 0 reserves the next generation number, creates
   the staging dir, and announces ``{generation, step, world_size}`` in
   ``ROUND.json`` (temp+fsync+rename). Workers find the round by matching
   the step they are publishing at — the supervisor's deterministic
   schedule guarantees every worker publishes at the same step boundaries.
2. **shard staging (phase 1)** — every worker writes its shard files into
   the staging dir, fsyncs them, and *votes* by atomically writing
   ``SHARD-<k>.json``: a per-shard manifest of sha256 digests + byte
   counts. A worker killed mid-write never votes; its half-written files
   are invisible to the protocol.
3. **commit (phase 2, worker 0 only)** — wait for all ``world_size``
   votes (bounded; a missing vote is a :class:`MeshTimeout`, never a
   partial commit), re-hash every staged file, cross-check each shard
   manifest byte for byte, fold the sorted ``name|digest`` stream into
   the **whole-mesh digest**, and write ``MANIFEST.json`` — the commit
   marker, format-identical to a single-writer manifest plus a ``mesh``
   section — into the staging dir.
4. **publication** — fsync, then ``os.replace`` the staging dir to
   ``generations/gen-N``: THE publication point, exactly as single-writer.
   Only after the rename does the ledger record the entry and GC run.
   Non-coordinator workers block on the rename becoming visible (bounded).

Crash analysis — why no failure can surface a torn generation:

- worker k killed mid-write (before its vote): no ``SHARD-k.json``, so
  worker 0 times out and aborts; the staging dir is never renamed.
- worker 0 killed after staging its own shard but before the commit
  marker: no ``MANIFEST.json``, no rename; peers time out on publication.
- worker 0 killed *between the commit marker and the ledger write*: the
  marker lives inside ``.mesh-stage-*`` — until the rename it is just
  bytes in a staging dir ``latest_valid()`` never scans. Killed after the
  rename, the generation is complete and the directory scan (not the
  ledger) defines liveness, exactly like the single-writer window.
- in every abort case the stale staging dir (and the gang's barrier
  files) are swept by the next gang's coordinator at construction —
  token-scoped, so a live gang never sweeps its own round.

Recovery model is **gang restart** (the TensorFlow system paper's
fault-tolerance design: consistent checkpoints + recovery as the only
correctness mechanism): any worker death aborts the whole gang via
bounded barrier timeouts (exit code 76 from the worker CLI), and the
relauncher restarts all N workers with a fresh ``token``. Restore is
*elastic*: ``GanExperiment.load_models`` merges however many shards the
generation holds, so a generation written by M workers restores
bit-exactly onto N workers for any N ≥ 1 (including the serve path).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import time
from typing import Callable, Dict, List, Optional

from gan_deeplearning4j_tpu.resilience.store import (
    CheckpointStore,
    Generation,
    MANIFEST_NAME,
    FORMAT_VERSION,
    _atomic_write_json,
    _fsync_dir,
    _hash_file,
    gen_dirname,
)
from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import TRACER

ROUND_NAME = "ROUND.json"
MESH_STAGE_PREFIX = ".mesh-stage-"

_TOKEN_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")
_STAGE_RE = re.compile(r"^\.mesh-stage-(?P<token>[A-Za-z0-9_.-]+)-"
                       r"(?P<gen>gen-\d{8})$")


class MeshTimeout(RuntimeError):
    """A bounded mesh wait expired: a peer is dead, wedged, or was never
    launched. Gang semantics make this non-retryable in-process — the
    whole mesh must be relaunched (worker CLI exit code 76)."""


class MeshProtocolError(RuntimeError):
    """The round's on-disk state contradicts the protocol (colliding shard
    files, a vote whose digests do not match the staged bytes, a round
    announcement disagreeing with this worker's step). Terminal: relaunch
    cannot fix a logic error."""


def shard_manifest_name(worker: int) -> str:
    return f"SHARD-{worker:05d}.json"


def mesh_digest(files: Dict[str, dict]) -> str:
    """The whole-mesh digest: sha256 over the sorted ``name|digest|bytes``
    stream of every staged file. One scalar that pins the entire N-writer
    generation — the commit marker stores it, and any reader can recompute
    it from the manifest alone."""
    h = hashlib.sha256()
    for name in sorted(files):
        meta = files[name]
        h.update(f"{name}|{meta['digest']}|{meta['bytes']}\n".encode())
    return "sha256:" + h.hexdigest()


class MeshCoordinator:
    """One worker's handle on the gang. ``worker`` 0 is the coordinator
    (commits generations, resolves restores); all workers share the store
    ``root`` and a per-gang-launch ``token`` (any stale round or barrier
    state from a *dead* gang carries a different token and is swept by the
    next coordinator's construction — a live gang never collides with a
    corpse). ``timeout_s`` bounds every in-round wait; ``boot_timeout_s``
    bounds the first rendezvous (restore resolution), which must absorb
    cold-start skew between worker processes. ``sleep`` is injectable so
    tests assert waits without wall-clock stalls. ``sweep=False`` skips
    the coordinator's stale-gang sweep — REQUIRED for barrier-only users
    (scripts/multihost_smoke.py) rendezvousing on a root where an
    unrelated checkpoint gang may be live: to the sweep, that gang's
    in-flight round is indistinguishable from a corpse."""

    def __init__(self, root: str, worker: int, world_size: int,
                 token: str = "r0", timeout_s: float = 60.0,
                 boot_timeout_s: Optional[float] = None,
                 poll_s: float = 0.05, faults=None,
                 sleep: Callable[[float], None] = time.sleep,
                 sweep: bool = True) -> None:
        if world_size < 1:
            raise ValueError("world_size must be >= 1")
        if not 0 <= worker < world_size:
            raise ValueError(f"worker {worker} outside mesh of "
                             f"{world_size}")
        if not _TOKEN_RE.match(token):
            raise ValueError(f"token {token!r} must match "
                             f"{_TOKEN_RE.pattern}")
        self.root = os.path.abspath(root)
        self.worker = worker
        self.world_size = world_size
        self.token = token
        self.timeout_s = timeout_s
        self.boot_timeout_s = (timeout_s if boot_timeout_s is None
                               else boot_timeout_s)
        self.poll_s = poll_s
        self.faults = faults
        self._sleep = sleep
        self.mesh_dir = os.path.join(self.root, ".mesh", token)
        os.makedirs(self.mesh_dir, exist_ok=True)
        # per-phase timings of the most recent publish (None until one
        # lands) — the supervisor stamps these into its summary timeline
        self.last_phases: Optional[dict] = None
        registry = get_registry()
        self._c_commits = registry.counter(
            "resilience_mesh_commits_total",
            "mesh generations committed by this worker (coordinator only)")
        self._h_commit = registry.histogram(
            "resilience_mesh_commit_seconds",
            "wall seconds per coordinated mesh publish (stage + barrier + "
            "commit + rename), per worker")
        self._c_aborts = registry.counter(
            "resilience_mesh_aborts_total",
            "mesh rounds abandoned on a bounded-wait timeout")
        self._g_generation = registry.gauge(
            "resilience_generation",
            "newest published generation in the store this process opened "
            "(-1 = none)")
        if self.is_coordinator and sweep:
            self._sweep_stale()

    # -- identity -------------------------------------------------------
    @property
    def is_coordinator(self) -> bool:
        return self.worker == 0

    # -- stale-gang sweeping --------------------------------------------
    def _sweep_stale(self) -> None:
        """Remove state left by DEAD gangs. Gang restart is the only path
        here — the relauncher starts a fresh coordinator only after the
        previous gang is fully gone — so anything already on disk at
        coordinator construction is a corpse. What may be swept follows
        ownership: staging dirs and restore decisions are created ONLY by
        a coordinator, and this gang's coordinator (us) has created none
        yet, so every existing one — our own token included, guarding a
        relauncher that (against the CLI contract) reused a token — is
        safe to remove. Barrier arrival markers are written by PEERS, and
        a same-token peer of THIS gang may already have arrived, so own-
        token barrier dirs are never touched (a reused token therefore
        still risks ghost arrivals — the fresh-token rule stands)."""
        for name in os.listdir(self.root):
            if _STAGE_RE.match(name):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
        meshes = os.path.join(self.root, ".mesh")
        for name in os.listdir(meshes):
            if name != self.token:
                shutil.rmtree(os.path.join(meshes, name),
                              ignore_errors=True)
        for name in os.listdir(self.mesh_dir):
            if name.startswith("restore-") and name.endswith(".json"):
                try:
                    os.unlink(os.path.join(self.mesh_dir, name))
                except OSError:
                    pass

    # -- primitive waits ------------------------------------------------
    def _wait_for(self, predicate: Callable[[], bool], what: str,
                  timeout_s: Optional[float] = None) -> None:
        deadline = time.monotonic() + (self.timeout_s if timeout_s is None
                                       else timeout_s)
        while not predicate():
            if time.monotonic() >= deadline:
                self._c_aborts.inc()
                TRACER.instant("resilience.mesh_timeout",
                               {"worker": self.worker, "what": what})
                raise MeshTimeout(
                    f"worker {self.worker}/{self.world_size} timed out "
                    f"waiting for {what} (gang abort — relaunch the mesh)")
            self._sleep(self.poll_s)

    def barrier(self, name: str, timeout_s: Optional[float] = None) -> None:
        """Meet the gang: arrive by atomically creating
        ``.mesh/<token>/<name>/w<worker>``, then wait (bounded) until all
        ``world_size`` arrival markers exist. Names must be unique per
        rendezvous within one token (the supervisor keys them by step)."""
        d = os.path.join(self.mesh_dir, name)
        os.makedirs(d, exist_ok=True)
        _atomic_write_json(os.path.join(d, f"w{self.worker:05d}"),
                           {"worker": self.worker, "at": time.time()})

        def all_arrived() -> bool:
            try:
                present = os.listdir(d)
            except OSError:
                return False
            return sum(1 for n in present if n.startswith("w")
                       and not n.endswith(".tmp")) >= self.world_size

        self._wait_for(all_arrived, f"barrier {name!r}", timeout_s)

    # -- coordinated restore --------------------------------------------
    def resolve_restore(self, store: CheckpointStore,
                        attempt: int = 0) -> Optional[Generation]:
        """One restore decision for the whole gang. Worker 0 runs
        ``latest_valid()`` — performing any quarantine moves exactly once —
        and publishes the chosen generation number as a decision file; the
        other workers wait for the decision and load that generation
        read-only. Without this, N workers would race their quarantine
        renames against each other's digest walks."""
        decision_path = os.path.join(self.mesh_dir,
                                     f"restore-{attempt}.json")
        if self.is_coordinator:
            generation = store.latest_valid()
            _atomic_write_json(decision_path, {
                "generation": None if generation is None
                else generation.number,
                "attempt": attempt,
            })
            return generation
        self._wait_for(lambda: os.path.exists(decision_path),
                       f"restore decision (attempt {attempt})",
                       self.boot_timeout_s)
        with open(decision_path) as fh:
            decision = json.load(fh)
        if decision["generation"] is None:
            return None
        return store.load(int(decision["generation"]))

    # -- the two-phase coordinated publish ------------------------------
    def _stage_dirname(self, number: int) -> str:
        return f"{MESH_STAGE_PREFIX}{self.token}-{gen_dirname(number)}"

    def _find_round(self, step: int) -> tuple:
        """Non-coordinator: locate the staging dir whose ``ROUND.json``
        announces this step (bounded wait). Returns (number, staging)."""
        found: Dict[str, tuple] = {}

        def round_visible() -> bool:
            for name in os.listdir(self.root):
                m = _STAGE_RE.match(name)
                if not m or m.group("token") != self.token:
                    continue
                try:
                    with open(os.path.join(self.root, name,
                                           ROUND_NAME)) as fh:
                        announced = json.load(fh)
                except (OSError, json.JSONDecodeError):
                    continue  # round dir exists, announcement not landed yet
                if int(announced.get("step", -1)) == step:
                    found["round"] = (int(announced["generation"]),
                                      os.path.join(self.root, name),
                                      announced)
                    return True
            return False

        self._wait_for(round_visible, f"round announcement for step {step}")
        number, staging, announced = found["round"]
        if int(announced.get("world_size", -1)) != self.world_size:
            raise MeshProtocolError(
                f"round for step {step} announces world_size "
                f"{announced.get('world_size')} but this worker joined a "
                f"mesh of {self.world_size}")
        return number, staging

    def publish(self, store: CheckpointStore,
                shard_writer: Callable[[str], List[str]], step: int,
                extra: Optional[dict] = None) -> Generation:
        """Coordinated publish of one mesh generation at ``step``. Every
        worker of the gang must call this at the same step with its own
        ``shard_writer(staging_dir) -> [filenames written]``. Returns the
        published :class:`Generation` on every worker; raises
        :class:`MeshTimeout` on any bounded wait expiring (gang abort —
        the staging dir is deliberately left for the post-mortem and the
        next gang's sweep, never half-cleaned under live peers)."""
        t0 = time.perf_counter()
        if self.is_coordinator:
            number = store.next_number()
            staging = os.path.join(self.root, self._stage_dirname(number))
            os.makedirs(staging)
            _atomic_write_json(os.path.join(staging, ROUND_NAME), {
                "format_version": FORMAT_VERSION,
                "generation": number,
                "step": int(step),
                "world_size": self.world_size,
                "token": self.token,
            })
        else:
            number, staging = self._find_round(step)
        t_announced = time.perf_counter()

        # -- phase 1: stage this worker's shard, then vote --------------
        if self.faults is not None:
            self.faults.on_shard_write(step)
        written = sorted(shard_writer(staging))
        if not written:
            raise MeshProtocolError(
                f"worker {self.worker} staged no files — an empty shard "
                f"can never be restored")
        files: Dict[str, dict] = {}
        for name in written:
            digest, size = _hash_file(os.path.join(staging, name),
                                      fsync=True)
            files[name] = {"digest": digest, "bytes": size}
        _atomic_write_json(os.path.join(staging, shard_manifest_name(
            self.worker)), {
            "format_version": FORMAT_VERSION,
            "worker": self.worker,
            "world_size": self.world_size,
            "generation": number,
            "step": int(step),
            "files": files,
        })
        _fsync_dir(staging)
        t_staged = time.perf_counter()
        if self.faults is not None:
            self.faults.on_shard_staged(step)

        final = os.path.join(store.generations_dir, gen_dirname(number))
        if self.is_coordinator:
            self._commit(store, staging, final, number, step, extra)
        else:
            # publication barrier: the rename becoming visible IS the
            # commit notification — no second marker to race with
            self._wait_for(lambda: os.path.isdir(final),
                           f"publication of generation {number}")
        t_committed = time.perf_counter()
        seconds = t_committed - t0
        # per-phase attribution (docs/OBSERVABILITY.md "straggler
        # attribution"): announce = round rendezvous (a worker whose
        # peers lag waits HERE), stage = this worker writing + hashing
        # its own shard (a straggler's time lands HERE), commit_wait =
        # votes + re-hash + rename on the coordinator, or the publication
        # barrier on everyone else (the fast writers' wait on the
        # straggler lands HERE). Stamped into the supervisor's summary
        # timeline and, when tracing, into per-phase spans that
        # trace_report's barrier table folds by (gen, worker).
        self.last_phases = {
            "announce_s": t_announced - t0,
            "stage_s": t_staged - t_announced,
            "commit_wait_s": t_committed - t_staged,
        }
        self._h_commit.observe(seconds)
        self._g_generation.set(number)
        if TRACER.enabled:
            span_args = {"gen": number, "step": int(step),
                         "worker": self.worker,
                         "coordinator": self.is_coordinator}
            TRACER.complete("resilience.mesh_stage", t_announced, t_staged,
                            dict(span_args))
            TRACER.complete("resilience.mesh_commit_wait", t_staged,
                            t_committed, dict(span_args))
            TRACER.complete("resilience.mesh_publish", t0, t_committed,
                            span_args)
        with open(os.path.join(final, MANIFEST_NAME)) as fh:
            manifest = json.load(fh)
        return Generation(number=number, path=final, manifest=manifest)

    def _commit(self, store: CheckpointStore, staging: str, final: str,
                number: int, step: int, extra: Optional[dict]) -> None:
        """Phase 2, coordinator only: all-votes barrier → whole-mesh
        digest → commit marker → atomic rename → ledger."""
        vote_names = [shard_manifest_name(k)
                      for k in range(self.world_size)]

        def all_voted() -> bool:
            return all(os.path.exists(os.path.join(staging, n))
                       for n in vote_names)

        self._wait_for(all_voted,
                       f"all {self.world_size} shard manifests for "
                       f"generation {number}")
        if self.faults is not None:
            self.faults.on_mesh_commit(step)

        shards: List[dict] = []
        claimed: Dict[str, int] = {}
        for k, name in enumerate(vote_names):
            with open(os.path.join(staging, name)) as fh:
                vote = json.load(fh)
            if (int(vote.get("worker", -1)) != k
                    or int(vote.get("generation", -1)) != number):
                raise MeshProtocolError(
                    f"shard manifest {name} does not belong to this round "
                    f"(worker {vote.get('worker')}, generation "
                    f"{vote.get('generation')})")
            for member in vote.get("files", {}):
                if member in claimed:
                    raise MeshProtocolError(
                        f"shard file {member!r} staged by both worker "
                        f"{claimed[member]} and worker {k} — shard "
                        f"writers must produce disjoint files")
                claimed[member] = k
            shards.append(vote)

        # re-hash EVERY staged byte (shard data, votes, the round file):
        # the combined manifest must pin what is actually on disk, and the
        # cross-check below catches a shard whose vote lied about it
        files: Dict[str, dict] = {}
        for name in sorted(os.listdir(staging)):
            digest, size = _hash_file(os.path.join(staging, name),
                                      fsync=True)
            files[name] = {"digest": digest, "bytes": size}
        for vote in shards:
            for member, meta in vote["files"].items():
                if files.get(member) != meta:
                    raise MeshProtocolError(
                        f"staged file {member!r} does not match worker "
                        f"{vote['worker']}'s shard manifest — torn shard "
                        f"write")

        manifest = {
            "format_version": FORMAT_VERSION,
            "generation": number,
            "step": int(step),
            "files": files,
            "mesh": {
                "world_size": self.world_size,
                "token": self.token,
                "mesh_digest": mesh_digest(files),
                "shards": vote_names,
            },
            **(extra or {}),
        }
        _atomic_write_json(os.path.join(staging, MANIFEST_NAME), manifest)
        if self.faults is not None:
            self.faults.on_mesh_committed(step)
        _fsync_dir(staging)
        os.replace(staging, final)  # THE publication point
        _fsync_dir(store.generations_dir)
        self._c_commits.inc()
        store.note_published(number, step)


__all__ = [
    "MeshCoordinator",
    "MeshTimeout",
    "MeshProtocolError",
    "mesh_digest",
    "shard_manifest_name",
]
