"""Deterministic fault injection — the test plane of the resilience layer.

Preemptible TPU workers fail in a handful of shapes: the process dies
mid-step (crash / OOM / segfault), the scheduler sends SIGTERM with a grace
window, a checkpoint write stalls or errors, or bytes rot on disk. Each
shape is a :class:`FaultSpec` kind:

- ``raise``       — raise :class:`InjectedFault` at the start of step N
                    (the trappable worker fault: exercises the supervisor's
                    in-process retry/backoff path);
- ``preempt``     — send SIGTERM to the current process at step N (the
                    scheduler-preemption shape: exercises checkpoint-then-
                    clean-exit);
- ``kill``        — SIGKILL the current process at step N (the untrappable
                    hard kill: only a *relauncher* — the drill — recovers);
- ``slow_write``  — sleep ``seconds`` inside the next checkpoint publish at
                    or after step N;
- ``fail_write``  — raise ``OSError`` inside that publish;
- ``corrupt``     — after the first publish at or after step N, flip bytes
                    in one member file of the published generation (the
                    bit-rot shape the store must quarantine).

Multi-worker (mesh) shapes — the ways an N-writer coordinated publish can
die, hooked by ``resilience/mesh.py``'s two-phase protocol:

- ``straggler``     — sleep ``seconds`` before staging this worker's shard
                      at the first mesh publish at or after step N (the
                      slow-writer shape: the commit must wait, not tear);
- ``kill_shard_staged`` — SIGKILL this worker right after its shard
                      manifest lands but before the mesh commit (a writer
                      dead inside the commit window);
- ``kill_commit``   — SIGKILL the coordinator after the all-shards barrier
                      but before the commit marker is written;
- ``kill_committed`` — SIGKILL the coordinator after the commit marker but
                      before the atomic rename / ledger write (the
                      marker-without-publication window).

Every spec may carry ``args: {"worker": k}`` to target one worker of a
shared schedule; an injector constructed with ``worker_id`` skips specs
aimed at other workers (specs without ``worker`` fire everywhere).

Schedules are *deterministic*: either an explicit spec list or
:meth:`FaultSchedule.seeded`, which derives (step, kind) pairs from a seed
via ``random.Random`` — the same seed always yields the same faults, so a
drill failure reproduces exactly. Schedules round-trip through JSON
(documented in docs/RESILIENCE.md) so a parent process can hand one to a
worker via a file path.

Every fired fault is recorded in ``FaultInjector.log`` — the drill's
ground truth for "the kill happened at step N".
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import time
from typing import Dict, List, Optional, Sequence

FORMAT_VERSION = 1

STEP_KINDS = ("raise", "preempt", "kill")
WRITE_KINDS = ("slow_write", "fail_write")
MESH_KINDS = ("straggler", "kill_shard_staged", "kill_commit",
              "kill_committed")
KINDS = STEP_KINDS + WRITE_KINDS + ("corrupt",) + MESH_KINDS


class InjectedFault(RuntimeError):
    """The trappable worker fault (``raise`` kind)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault. ``step`` semantics: ``raise``/``preempt``/
    ``kill`` fire exactly at the start of step ``step``; write/corrupt
    kinds fire on the first checkpoint publish at or after ``step`` (a
    publish may not land on an arbitrary step, so exact match would make
    those faults silently unreachable)."""

    kind: str
    step: int
    args: Dict = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(known: {', '.join(KINDS)})")
        if self.step < 0:
            raise ValueError("fault step must be >= 0")


@dataclasses.dataclass
class FaultSchedule:
    """An ordered, deterministic set of faults."""

    specs: List[FaultSpec] = dataclasses.field(default_factory=list)

    @classmethod
    def seeded(cls, seed: int, total_steps: int,
               kinds: Sequence[str] = ("raise",),
               n_faults: int = 1) -> "FaultSchedule":
        """A seeded random schedule: ``n_faults`` distinct steps in
        ``[1, total_steps)`` with kinds drawn from ``kinds`` — the same
        seed always yields the same schedule."""
        if total_steps < 2:
            raise ValueError("total_steps must be >= 2 to place a fault")
        rng = random.Random(seed)
        n = min(n_faults, total_steps - 1)
        steps = sorted(rng.sample(range(1, total_steps), n))
        return cls([FaultSpec(kind=rng.choice(list(kinds)), step=s)
                    for s in steps])

    def to_json(self, path: str) -> None:
        payload = {
            "format_version": FORMAT_VERSION,
            "faults": [
                {"kind": s.kind, "step": s.step, "args": s.args}
                for s in self.specs
            ],
        }
        with open(path, "w") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")

    @classmethod
    def from_json(cls, path: str) -> "FaultSchedule":
        with open(path) as fh:
            payload = json.load(fh)
        if payload.get("format_version", 0) > FORMAT_VERSION:
            raise ValueError(
                f"fault schedule format {payload['format_version']} is newer "
                f"than supported {FORMAT_VERSION}")
        return cls([
            FaultSpec(kind=f["kind"], step=int(f["step"]),
                      args=dict(f.get("args", {})))
            for f in payload.get("faults", [])
        ])


def corrupt_generation(store, number: int, seed: int = 0,
                       member: Optional[str] = None) -> str:
    """Flip 8 bytes in the middle of one member file of a *published*
    generation — in place, size-preserving, seeded member choice. Returns
    the corrupted member name. The store's digest verification must
    subsequently quarantine the generation; that is the invariant the
    drill checks."""
    path = os.path.join(store.generations_dir,
                        f"gen-{number:08d}")
    from gan_deeplearning4j_tpu.resilience.store import MANIFEST_NAME

    members = sorted(
        n for n in os.listdir(path)
        if n != MANIFEST_NAME and os.path.isfile(os.path.join(path, n))
    )
    if not members:
        raise ValueError(f"generation {number} has no members to corrupt")
    name = member or random.Random(seed).choice(members)
    fp = os.path.join(path, name)
    size = os.path.getsize(fp)
    offset = max(0, size // 2 - 4)
    with open(fp, "r+b") as fh:
        fh.seek(offset)
        chunk = fh.read(8)
        fh.seek(offset)
        fh.write(bytes(b ^ 0xFF for b in chunk))
        fh.flush()
        os.fsync(fh.fileno())
    return name


class FaultInjector:
    """Executes a :class:`FaultSchedule` against the supervisor's hook
    points. Each spec fires at most once. ``sleep`` is injectable so tests
    assert slow-write behavior without wall-clock waits."""

    def __init__(self, schedule: Optional[FaultSchedule] = None,
                 sleep=time.sleep, worker_id: Optional[int] = None) -> None:
        self.schedule = schedule or FaultSchedule()
        self._sleep = sleep
        self.worker_id = worker_id
        self._fired: set = set()
        self.log: List[dict] = []

    def _aimed_at_me(self, spec: FaultSpec) -> bool:
        """A spec with ``args.worker`` targets ONE worker of a shared
        schedule; without it (or without a worker identity) it fires
        everywhere — single-process schedules keep working unchanged."""
        target = spec.args.get("worker")
        return (target is None or self.worker_id is None
                or int(target) == self.worker_id)

    def _take(self, kinds, predicate):
        for i, spec in enumerate(self.schedule.specs):
            if i in self._fired or spec.kind not in kinds:
                continue
            if not self._aimed_at_me(spec):
                continue
            if predicate(spec):
                self._fired.add(i)
                yield spec

    def _record(self, spec: FaultSpec, step: int) -> None:
        self.log.append({"kind": spec.kind, "scheduled_step": spec.step,
                         "fired_step": step, "at": time.time()})

    # -- hook points ----------------------------------------------------
    def on_step(self, step: int) -> None:
        """Called by the supervisor at the START of every training step."""
        for spec in self._take(STEP_KINDS, lambda s: s.step == step):
            self._record(spec, step)
            if spec.kind == "raise":
                raise InjectedFault(
                    f"injected worker fault at step {step}")
            if spec.kind == "preempt":
                os.kill(os.getpid(), signal.SIGTERM)
                return  # handler runs on this signal's delivery
            if spec.kind == "kill":
                os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, by design

    def on_checkpoint_write(self, step: int) -> None:
        """Called by the store at the start of every publish."""
        for spec in self._take(WRITE_KINDS, lambda s: step >= s.step):
            self._record(spec, step)
            if spec.kind == "slow_write":
                self._sleep(float(spec.args.get("seconds", 1.0)))
            elif spec.kind == "fail_write":
                raise OSError(
                    f"injected checkpoint write failure at step {step}")

    # -- mesh (two-phase publish) hook points ---------------------------
    def _kill_at(self, kind: str, step: int) -> None:
        for spec in self._take((kind,), lambda s: step >= s.step):
            self._record(spec, step)
            os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, by design

    def on_shard_write(self, step: int) -> None:
        """Called by the mesh publish before this worker stages its shard
        (``straggler`` sleeps here — the slow-writer shape)."""
        for spec in self._take(("straggler",), lambda s: step >= s.step):
            self._record(spec, step)
            self._sleep(float(spec.args.get("seconds", 1.0)))

    def on_shard_staged(self, step: int) -> None:
        """Called after this worker's shard manifest (its phase-1 vote)
        lands, before the mesh commit."""
        self._kill_at("kill_shard_staged", step)

    def on_mesh_commit(self, step: int) -> None:
        """Coordinator only: after the all-shards barrier, before the
        commit marker is written."""
        self._kill_at("kill_commit", step)

    def on_mesh_committed(self, step: int) -> None:
        """Coordinator only: after the commit marker, before the atomic
        rename publishes it and the ledger records it."""
        self._kill_at("kill_committed", step)

    def on_published(self, store, generation) -> None:
        """Called by the supervisor after every successful publish."""
        for spec in self._take(("corrupt",),
                               lambda s: generation.step >= s.step):
            self._record(spec, generation.step)
            name = corrupt_generation(
                store, generation.number,
                seed=int(spec.args.get("seed", 0)),
                member=spec.args.get("member"),
            )
            self.log[-1]["member"] = name
