"""Generation-ledgered checkpoint store — the durability layer under the
fault-tolerant supervisor.

A *generation* is one immutable, self-verifying checkpoint directory:

```
<root>/
  ledger.json                      # the generation ledger (atomic updates)
  generations/
    gen-00000007/
      MANIFEST.json                # per-file content digests + step + extras
      mnist_dis_model.zip          # whatever the writer callback produced
      ...
  quarantine/
    gen-00000006/                  # failed verification — kept for forensics,
                                   # never selected as "latest"
  .stage-...                       # transient staging dirs (crash leftovers
                                   # are swept at store construction)
```

Publish protocol (crash-safe at every point):

1. the writer callback populates a fresh ``.stage-*`` directory;
2. ``MANIFEST.json`` (sha256 digest + byte count per file, the step counter,
   caller extras) is written temp+fsync+rename *inside* the staging dir;
3. every file and the staging dir itself are fsynced;
4. ``os.replace`` renames the staging dir to ``generations/gen-N`` — the
   atomic publication point: a reader either sees the complete generation
   or nothing;
5. the ledger records the entry and retention GC runs.

A crash before (4) leaves only a staging dir (swept later); a crash after
(4) but before (5) leaves a published-but-unledgered generation — the read
side scans the ``generations/`` directory, not the ledger, precisely so
that window loses nothing. The ledger is the *bookkeeping* record: status
transitions (``published`` → ``quarantined`` / ``gc``) and the reasons for
them, which is what the drill asserts its invariants against.

Read side: ``latest_valid()`` walks published generations newest-first,
re-hashing every file against its manifest; a corrupt or truncated
generation is moved to ``quarantine/`` and *flagged in the ledger*, and the
walk falls back to the previous generation — a half-written or bit-flipped
checkpoint is never served as "latest".
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import shutil
import tempfile
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import TRACER
from gan_deeplearning4j_tpu.utils.serializer import _flatten

MANIFEST_NAME = "MANIFEST.json"
LEDGER_NAME = "ledger.json"
FORMAT_VERSION = 1

_GEN_RE = re.compile(r"^gen-(\d{8})$")


def gen_dirname(number: int) -> str:
    return f"gen-{number:08d}"


def tree_digest(tree) -> str:
    """Canonical content digest of a pytree of arrays: sha256 over the
    sorted ``path|dtype|shape|raw bytes`` stream. Unlike a digest of the
    checkpoint *zip* (whose deflate stream embeds member timestamps), this
    is reproducible across runs and processes — the currency of the drill's
    bit-exact-resume invariant."""
    import jax

    flat: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        _flatten("t", tree, flat)
    else:  # TrainState-like: digest params + updater + step
        _flatten("t/params", tree.params, flat)
        _flatten("t/updater", tree.opt_state, flat)
        flat["t/step"] = tree.step
    flat = jax.device_get(flat)
    h = hashlib.sha256()
    for key in sorted(flat):
        a = np.asarray(flat[key])
        h.update(key.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return "sha256:" + h.hexdigest()


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _hash_file(path: str, fsync: bool = False) -> Tuple[str, int]:
    """(digest, byte count) of a file, streamed in 1 MiB chunks — constant
    memory on checkpoints of any size. ``fsync=True`` additionally fsyncs
    the same descriptor (one open per file on the publish path)."""
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
            n += len(chunk)
        if fsync:
            os.fsync(fh.fileno())
    return "sha256:" + h.hexdigest(), n


def _atomic_write_json(path: str, payload: dict) -> None:
    """temp + fsync + rename — the only way any metadata file here lands."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


@dataclasses.dataclass
class Generation:
    """One verified, readable generation."""

    number: int
    path: str
    manifest: dict

    @property
    def step(self) -> int:
        return int(self.manifest.get("step", 0))

    def file(self, name: str) -> str:
        return os.path.join(self.path, name)


class CheckpointStore:
    """The generation-ledgered store. ``keep_last`` newest published
    generations survive GC unconditionally; additionally every
    ``keep_every``-th generation number is kept forever (0 = off) — the
    keep-last-K + keep-every-N retention policy. A ``fault_injector``
    (``faults.FaultInjector``) hooks the write path for the drill's
    slow/failed-write scenarios; production passes None."""

    def __init__(self, root: str, keep_last: int = 3, keep_every: int = 0,
                 fault_injector=None, read_retries: int = 2,
                 read_retry_backoff_s: float = 0.05,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1 (the store must always "
                             "retain a newest generation)")
        if keep_every < 0:
            raise ValueError("keep_every must be >= 0 (0 = off)")
        if read_retries < 0:
            raise ValueError("read_retries must be >= 0 (0 = no retries)")
        self.root = os.path.abspath(root)
        self.keep_last = keep_last
        self.keep_every = keep_every
        self.faults = fault_injector
        self.read_retries = read_retries
        self.read_retry_backoff_s = read_retry_backoff_s
        self._sleep = sleep
        self.generations_dir = os.path.join(self.root, "generations")
        self.quarantine_dir = os.path.join(self.root, "quarantine")
        os.makedirs(self.generations_dir, exist_ok=True)
        os.makedirs(self.quarantine_dir, exist_ok=True)
        # sweep crash leftovers: an unrenamed staging dir was never published
        for name in os.listdir(self.root):
            if name.startswith(".stage-"):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
        # telemetry registry series (docs/OBSERVABILITY.md): the ledger
        # stays the durable record; these are the live process-wide view
        registry = get_registry()
        self._c_publishes = registry.counter(
            "resilience_publishes_total", "generations published")
        self._h_publish = registry.histogram(
            "resilience_publish_seconds",
            "wall seconds per store publish (write+digest+fsync+rename)")
        self._c_quarantines = registry.counter(
            "resilience_quarantines_total",
            "generations moved to quarantine on failed verification")
        self._g_generation = registry.gauge(
            "resilience_generation",
            "newest published generation in the store this process opened "
            "(-1 = none)")
        self._c_read_retries = registry.counter(
            "resilience_read_retries_total",
            "transient OSError store reads retried before verify/load "
            "passed judgment (shared-filesystem flakes, not corruption)")
        # initialize from the directory scan: a fresh store must read -1,
        # not the gauge's 0.0 default — generation 0 is a REAL generation
        existing = self.published()
        self._g_generation.set(existing[-1] if existing else -1)

    # -- ledger ---------------------------------------------------------
    @property
    def ledger_path(self) -> str:
        return os.path.join(self.root, LEDGER_NAME)

    def ledger(self) -> dict:
        try:
            with open(self.ledger_path) as fh:
                return json.load(fh)
        except (FileNotFoundError, json.JSONDecodeError):
            # a torn ledger is recoverable: the generations/ dir scan is the
            # source of truth for what exists; the ledger restarts empty
            return {"format_version": FORMAT_VERSION, "entries": {}}

    def _update_ledger(self, number: int, **fields) -> None:
        ledger = self.ledger()
        entry = ledger["entries"].setdefault(str(number), {})
        entry.update(fields)
        _atomic_write_json(self.ledger_path, ledger)

    def entry(self, number: int) -> dict:
        return self.ledger()["entries"].get(str(number), {})

    # -- enumeration ----------------------------------------------------
    def _scan(self, directory: str) -> List[int]:
        out = []
        for name in os.listdir(directory):
            m = _GEN_RE.match(name)
            if m and os.path.isdir(os.path.join(directory, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def published(self) -> List[int]:
        """Generation numbers currently live under ``generations/``
        (ascending). The directory scan — not the ledger — defines
        liveness, so a publish that crashed before its ledger write still
        counts."""
        return self._scan(self.generations_dir)

    def quarantined(self) -> List[int]:
        return self._scan(self.quarantine_dir)

    def generations_newer_than(self, number: Optional[int]) -> List[int]:
        """Published generation numbers strictly newer than ``number``
        (ascending; all of them when ``number`` is None) — the reload
        plane's ledger lookup: a watcher tracking the served generation
        asks only for what it has not seen yet."""
        published = self.published()
        if number is None:
            return published
        return [n for n in published if n > number]

    def next_number(self) -> int:
        """Monotonic across GC and quarantine: one more than anything the
        directories or the ledger have ever seen."""
        seen = self.published() + self.quarantined()
        ledger_nums = [int(k) for k in self.ledger()["entries"]]
        return max(seen + ledger_nums, default=-1) + 1

    # -- publish --------------------------------------------------------
    def publish(self, writer: Callable[[str], None], step: int,
                extra: Optional[dict] = None) -> Generation:
        """Publish one generation. ``writer(staging_dir)`` populates the
        directory; everything it wrote is digested into the manifest and
        becomes immutable once the atomic rename lands."""
        number = self.next_number()
        t_publish = time.perf_counter()
        staging = os.path.join(
            self.root, f".stage-{gen_dirname(number)}-{os.getpid()}"
        )
        os.makedirs(staging)
        try:
            if self.faults is not None:
                self.faults.on_checkpoint_write(step)
            writer(staging)
            files: Dict[str, dict] = {}
            for name in sorted(os.listdir(staging)):
                # one streamed pass per file: digest AND fsync on the same
                # descriptor — constant memory however large the checkpoint
                digest, size = _hash_file(os.path.join(staging, name),
                                          fsync=True)
                files[name] = {"digest": digest, "bytes": size}
            if not files:
                raise ValueError("publish writer produced no files — an "
                                 "empty generation can never be restored")
            manifest = {
                "format_version": FORMAT_VERSION,
                "generation": number,
                "step": int(step),
                "files": files,
                **(extra or {}),
            }
            # the manifest itself is fsynced inside _atomic_write_json
            _atomic_write_json(os.path.join(staging, MANIFEST_NAME), manifest)
            _fsync_dir(staging)
            final = os.path.join(self.generations_dir, gen_dirname(number))
            os.replace(staging, final)  # THE publication point
            _fsync_dir(self.generations_dir)
        except BaseException:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        # measure to the publication point: ledger bookkeeping and
        # retention GC below are not publish cost, and folding them in
        # would inflate exactly the checkpoint-overhead number the drill
        # reports (the metric's help text pins write+digest+fsync+rename)
        t_published = time.perf_counter()
        self._h_publish.observe(t_published - t_publish)
        TRACER.complete("resilience.publish", t_publish, t_published,
                        {"gen": number, "step": int(step),
                         "kind": (extra or {}).get("kind", "training")})
        self.note_published(number, step)
        return Generation(number=number, path=final, manifest=manifest)

    def note_published(self, number: int, step: int) -> None:
        """Post-rename bookkeeping for a generation published by an
        EXTERNAL committer (the mesh coordinator's two-phase publish lands
        its own atomic rename): publish counter + gauge, the ledger entry,
        and retention GC — one definition with :meth:`publish`'s own
        epilogue so single-writer and mesh generations age identically."""
        self._c_publishes.inc()
        self._g_generation.set(number)
        self._update_ledger(number, status="published", step=int(step),
                            published_at=time.time())
        self.gc()

    # -- read side ------------------------------------------------------
    def _retried_read(self, fn: Callable[[], "object"]):
        """Run a read, retrying transient ``OSError`` with capped
        exponential backoff before giving up. Shared-filesystem multi-host
        runs (NFS-style mounts under the mesh plane) see sporadic EIO /
        ESTALE on perfectly good bytes — without the retry, one flaky read
        inside :meth:`verify` condemns a good generation to quarantine.
        ``read_retries=0`` restores fail-fast. The final error propagates
        to the caller, which still judges it exactly as before."""
        attempt = 0
        while True:
            try:
                return fn()
            except OSError:
                attempt += 1
                if attempt > self.read_retries:
                    raise
                self._c_read_retries.inc()
                self._sleep(min(1.0, self.read_retry_backoff_s
                                * 2 ** (attempt - 1)))

    def _read_manifest(self, path: str) -> dict:
        def read():
            with open(os.path.join(path, MANIFEST_NAME)) as fh:
                return json.load(fh)
        return self._retried_read(read)

    def verify(self, number: int) -> Optional[str]:
        """None when generation ``number`` is intact; otherwise the reason
        it is not (unparseable/missing manifest, missing member, size or
        digest mismatch). Transient ``OSError`` reads are retried
        (``read_retries`` with capped backoff) before a generation is
        condemned — corruption verdicts stay immediate (a digest mismatch
        is deterministic; re-reading cannot fix it)."""
        path = os.path.join(self.generations_dir, gen_dirname(number))
        try:
            manifest = self._read_manifest(path)
        except (OSError, json.JSONDecodeError) as exc:
            return f"manifest unreadable: {exc}"
        if manifest.get("format_version", 0) > FORMAT_VERSION:
            return (f"manifest format {manifest['format_version']} is newer "
                    f"than supported {FORMAT_VERSION}")
        for name, meta in manifest.get("files", {}).items():
            try:
                digest, size = self._retried_read(
                    lambda name=name: _hash_file(os.path.join(path, name)))
            except OSError as exc:
                return f"member {name!r} unreadable: {exc}"
            if size != meta["bytes"]:
                return (f"member {name!r} truncated: {size} bytes, "
                        f"manifest says {meta['bytes']}")
            if digest != meta["digest"]:
                return f"member {name!r} fails digest verification"
        return None

    def load(self, number: int) -> Generation:
        """Verified read of one specific generation (raises on corruption —
        callers wanting fallback use :meth:`latest_valid`)."""
        reason = self.verify(number)
        if reason is not None:
            raise ValueError(
                f"generation {number} fails verification: {reason}")
        path = os.path.join(self.generations_dir, gen_dirname(number))
        manifest = self._read_manifest(path)
        return Generation(number=number, path=path, manifest=manifest)

    def latest_valid(self) -> Optional[Generation]:
        """The newest generation that passes digest verification. Anything
        newer that fails is quarantined (moved aside + ledger-flagged) so
        it can never be selected again; None when no valid generation
        exists."""
        for number in reversed(self.published()):
            reason = self.verify(number)
            if reason is None:
                return self.load(number)
            self.quarantine(number, reason)
        return None

    def quarantine(self, number: int, reason: str) -> None:
        """Move a corrupt generation out of the selectable set, keeping its
        bytes for forensics, and record why in the ledger."""
        src = os.path.join(self.generations_dir, gen_dirname(number))
        dst = os.path.join(self.quarantine_dir, gen_dirname(number))
        if os.path.isdir(src):
            if os.path.isdir(dst):  # name collision from a prior half-move
                shutil.rmtree(dst, ignore_errors=True)
            os.replace(src, dst)
        self._update_ledger(number, status="quarantined", reason=reason,
                            quarantined_at=time.time())
        self._c_quarantines.inc()
        TRACER.instant("resilience.quarantine",
                       {"gen": number, "reason": reason})

    # -- retention ------------------------------------------------------
    def retained(self, numbers: List[int]) -> set:
        keep = set(numbers[-self.keep_last:])
        if self.keep_every:
            keep.update(n for n in numbers if n % self.keep_every == 0)
        return keep

    def gc(self) -> List[int]:
        """Apply retention: delete published generations outside
        keep-last-K / keep-every-N. The ledger entry flips to ``gc``
        BEFORE the directory is removed — a crash mid-delete leaves a
        directory the next ``latest_valid`` can still verify (it only
        shrinks the retained set, never corrupts it)."""
        numbers = self.published()
        keep = self.retained(numbers)
        removed = []
        for number in numbers:
            if number in keep:
                continue
            self._update_ledger(number, status="gc", gc_at=time.time())
            shutil.rmtree(
                os.path.join(self.generations_dir, gen_dirname(number)),
                ignore_errors=True,
            )
            removed.append(number)
        return removed
