"""TrainingSupervisor — runs ``GanExperiment`` in resumable segments.

The contract (docs/RESILIENCE.md):

- **restore**: every attempt starts from the newest *valid* generation in
  the :class:`~.store.CheckpointStore` — params, updater state, and the
  step counter all come back (``GanExperiment.load_models``), and the PRNG
  stream needs no side file because every per-step key is derived from the
  carried step counter (``fold_in(base_key, step)``) and the label-noise
  draws replay deterministically from the config seed at construction;
- **deterministic data schedule**: the batch for step *i* is a pure
  function of *i* (sequential slices of the training arrays, wrapping at
  the epoch boundary) — the property that makes an interrupted-and-resumed
  run replay the exact minibatch sequence of an uninterrupted one;
- **bit-exact resume**: the two properties above make resume exact — an
  interrupted run resumed from any generation produces bit-identical final
  params to an uninterrupted run of equal total steps (the drill's first
  invariant, enforced by digest comparison);
- **fault trapping**: a worker fault (any ``Exception`` out of the training
  step — including :class:`~.faults.InjectedFault`) abandons the attempt
  and retries from the newest valid generation with bounded exponential
  backoff; the retry budget exhausting raises
  :class:`RetryBudgetExceeded` — a *terminal* error, never a silent loop;
- **preemption**: SIGTERM (or :meth:`request_preemption`) is honored at
  the next step boundary by publishing a checkpoint and returning cleanly
  with ``status="preempted"`` — the worker loses at most the in-flight
  step;
- **hard kills** (SIGKILL, machine loss) cannot be trapped in-process; the
  supervisor's contribution is that the store always holds a consistent
  generation at most ``publish_every`` steps old, so the *relauncher* (the
  drill, an orchestrator, a human) recovers by simply starting a new
  supervisor on the same store.
"""

from __future__ import annotations

import dataclasses
import logging
import signal
import time
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from gan_deeplearning4j_tpu.resilience.store import CheckpointStore, tree_digest
from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import TRACER

logger = logging.getLogger(__name__)


class RetryBudgetExceeded(RuntimeError):
    """Terminal: the configured retries are spent. Carries the last worker
    fault as ``__cause__``."""


class UnsupportedExperimentError(RuntimeError):
    """Terminal, never retried: the experiment config cannot honor the
    bit-exact resume contract. The contract rests on every random draw
    being a pure function of the carried step counter — true on the fused
    training path (per-step ``fold_in`` keys), false on the phased
    parameter-averaging path, whose z/label draws come from host-side
    *sequential* RNGs that a relaunched process cannot fast-forward."""


@dataclasses.dataclass
class SupervisorConfig:
    """Knobs of the resumable-segment loop (experiment knobs stay on
    ``ExperimentConfig``)."""

    total_steps: int
    publish_every: int = 10        # checkpoint cadence, in steps
    max_retries: int = 3           # worker-fault retries before terminal
    backoff_base_s: float = 0.5    # retry n sleeps min(base·2^(n-1), max)
    backoff_max_s: float = 30.0
    keep_last: int = 3             # store retention: newest K generations
    keep_every: int = 0            # plus every N-th generation (0 = off)
    serve_publish_every: int = 0   # serving-bundle cadence when a serve
    # store is wired (deploy/ reload plane); 0 = follow publish_every

    def validate(self) -> "SupervisorConfig":
        if self.total_steps < 1:
            raise ValueError("total_steps must be >= 1")
        if self.publish_every < 1:
            raise ValueError("publish_every must be >= 1")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.backoff_base_s < 0 or self.backoff_max_s < self.backoff_base_s:
            raise ValueError("need 0 <= backoff_base_s <= backoff_max_s")
        if self.serve_publish_every < 0:
            raise ValueError("serve_publish_every must be >= 0 (0 = follow "
                             "publish_every)")
        return self


class TrainingSupervisor:
    """Drives a :class:`GanExperiment` step by step under the fault
    contract above. ``features``/``labels`` are the full training arrays
    (the deterministic schedule slices them); ``sleep`` is injectable so
    tests assert backoff without wall-clock waits; ``experiment_factory``
    is injectable for fakes."""

    def __init__(self, exp_config, sup_config: SupervisorConfig,
                 features: np.ndarray, labels: np.ndarray,
                 store: Optional[CheckpointStore] = None,
                 store_root: Optional[str] = None,
                 faults=None,
                 sleep: Callable[[float], None] = time.sleep,
                 experiment_factory=None,
                 serve_store: Optional[CheckpointStore] = None,
                 serve_store_root: Optional[str] = None,
                 mesh=None) -> None:
        self.exp_config = exp_config
        self.sup = sup_config.validate()
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        b = exp_config.batch_size_train
        if self.features.shape[0] < b:
            raise ValueError(
                f"need at least one full batch: {self.features.shape[0]} "
                f"rows < batch_size_train {b}")
        self.batches_per_epoch = self.features.shape[0] // b
        if store is None:
            if store_root is None:
                raise ValueError("pass store= or store_root=")
            store = CheckpointStore(store_root, keep_last=self.sup.keep_last,
                                    keep_every=self.sup.keep_every,
                                    fault_injector=faults)
        self.store = store
        # the OTHER store: inference bundles for a live server's reload
        # plane (deploy/). Kept separate from the training store so
        # checkpoint retention and bundle retention never fight, and a
        # serving watcher never scans past training generations.
        if serve_store is None and serve_store_root is not None:
            serve_store = CheckpointStore(
                serve_store_root, keep_last=self.sup.keep_last,
                keep_every=self.sup.keep_every)
        self.serve_store = serve_store
        self._serve_every = (self.sup.serve_publish_every
                             or self.sup.publish_every)
        # mesh-coordinated mode (resilience/mesh.py): this worker is one
        # of N sharded checkpoint writers; restores are resolved once for
        # the gang and publishes go through the two-phase commit
        self.mesh = mesh
        self.faults = faults
        self._sleep = sleep
        if experiment_factory is None:
            from gan_deeplearning4j_tpu.harness import GanExperiment

            experiment_factory = GanExperiment
        self._experiment_factory = experiment_factory
        self._preempt = False
        self.retry_delays: List[float] = []
        self.events: List[dict] = []
        # mesh mode: the updater shard this worker last wrote (index,
        # count, files) — surfaced in the summary so drill invariant
        # messages can name the owning worker on a shard mismatch
        self._last_shard: Optional[dict] = None
        # per-phase step timeline (bounded: newest entries win) — one
        # record per train step and per publish, stamped with wall-epoch
        # start times so N workers' summaries line up on one clock for
        # straggler attribution (scripts/trace_report.py folds the same
        # story from merged traces; the summary is the trace-free form)
        self._timeline: deque = deque(maxlen=4096)
        # telemetry registry series (docs/OBSERVABILITY.md); the events
        # list above remains the drill's per-run record
        registry = get_registry()
        self._c_steps = registry.counter(
            "resilience_steps_total", "training steps completed")
        self._c_restores = registry.counter(
            "resilience_restores_total", "restores from a store generation")
        self._c_faults = registry.counter(
            "resilience_faults_total", "trapped worker faults (retried)")
        self._c_serve_publishes = registry.counter(
            "resilience_serve_publishes_total",
            "serving bundles published for the reload plane")

    # -- preemption -----------------------------------------------------
    def request_preemption(self) -> None:
        """Checkpoint and exit cleanly at the next step boundary."""
        self._preempt = True

    def install_signal_handlers(self) -> None:
        """SIGTERM-style preemption: the scheduler's grace signal becomes a
        clean checkpoint-and-exit instead of a dead worker."""
        def handler(signum, frame):
            logger.info("signal %d — preemption requested", signum)
            self.request_preemption()

        signal.signal(signal.SIGTERM, handler)

    # -- deterministic data schedule -------------------------------------
    def batch_at(self, step: int):
        """The minibatch for step ``step`` — a pure function of the step
        counter (sequential full batches, wrapping at the epoch boundary),
        so resumed and uninterrupted runs replay the same data stream."""
        b = self.exp_config.batch_size_train
        p = (step % self.batches_per_epoch) * b
        return self.features[p:p + b], self.labels[p:p + b]

    # -- state digests ---------------------------------------------------
    @staticmethod
    def state_digests(exp) -> dict:
        """Canonical content digests of every trained state — reproducible
        across processes (unlike zip bytes), the currency of the drill's
        bit-exactness check. Experiments expose ``digest_states()`` for
        the canonical tree-form view (under update sharding the packed
        updater rows are unpacked first, so replicated and sharded runs
        digest identically when the math agrees); fakes without it are
        digested as-is."""
        if hasattr(exp, "digest_states"):
            return {name: tree_digest(state)
                    for name, state in exp.digest_states().items()}
        out = {
            "dis": tree_digest(exp.dis_state),
            "gan": tree_digest(exp.gan_state),
            "gen": tree_digest(exp.gen_params),
        }
        if exp.cv_state is not None:
            out["CV"] = tree_digest(exp.cv_state)
        return out

    # -- publish ---------------------------------------------------------
    def _publish(self, exp) -> dict:
        t0 = time.perf_counter()
        digests = self.state_digests(exp)
        extra = {"kind": "training", "state_digests": digests}
        shard_files: List[str] = []
        if self.mesh is not None:
            # coordinated mesh publish: THIS worker stages only its shard;
            # worker 0's two-phase commit makes the generation visible for
            # everyone (every worker blocks until publication or timeout)
            def shard_writer(d: str) -> List[str]:
                files = exp.save_model_shard(
                    d, self.mesh.worker, self.mesh.world_size)
                shard_files.extend(files)
                return files

            generation = self.mesh.publish(
                self.store, shard_writer, step=exp.batch_counter,
                extra=extra,
            )
        else:
            generation = self.store.publish(
                lambda d: exp.save_models(directory=d),
                step=exp.batch_counter,
                extra=extra,
            )
        seconds = time.perf_counter() - t0
        event = {
            "event": "publish", "generation": generation.number,
            "step": exp.batch_counter, "seconds": seconds,
        }
        phases = (getattr(self.mesh, "last_phases", None)
                  if self.mesh is not None else None)
        if phases is not None:
            # announce/stage/commit_wait breakdown (resilience/mesh.py):
            # names whether THIS worker was the slow shard writer or the
            # one waiting at the publication barrier
            event["phases"] = dict(phases)
        self._timeline.append({
            "phase": "publish", "step": exp.batch_counter,
            "start_unix_s": round(time.time() - seconds, 6),
            "seconds": round(seconds, 6),
            "generation": generation.number,
            **({"phases": {k: round(v, 6) for k, v in phases.items()}}
               if phases is not None else {}),
        })
        if self.mesh is not None:
            # surface which updater shard this worker wrote — until now
            # only the file names encoded it, so a drill shard mismatch
            # could not name the owning worker
            event.update({
                "shard_index": self.mesh.worker,
                "shard_count": self.mesh.world_size,
                "shard_files": sorted(shard_files),
            })
            self._last_shard = {
                "worker": self.mesh.worker,
                "shard_index": self.mesh.worker,
                "shard_count": self.mesh.world_size,
                "files": sorted(shard_files),
            }
        self.events.append(event)
        if self.faults is not None and (self.mesh is None
                                        or self.mesh.is_coordinator):
            # post-publish faults (corrupt) mutate the published bytes —
            # exactly one worker may fire them, or double byte-flips on
            # one member would cancel back to valid bytes
            self.faults.on_published(self.store, generation)
        return {"generation": generation.number, "seconds": seconds,
                "digests": digests}

    def _publish_serving(self, exp) -> dict:
        """Publish the inference bundle (generator + classifier, no
        updater state) as a digest-verified generation of the SERVE
        store — what a live server's reload plane (deploy/) watches. Pure
        observation of the current state: training is unaffected, and the
        bit-exact-resume contract never depends on these bundles."""
        t0 = time.perf_counter()
        info = exp.publish_for_serving(store=self.serve_store)
        seconds = time.perf_counter() - t0
        self._c_serve_publishes.inc()
        self.events.append({
            "event": "serve_publish", "generation": info.get("generation"),
            "step": exp.batch_counter, "seconds": seconds,
        })
        return info

    # -- the loop ---------------------------------------------------------
    def run(self) -> dict:
        """Run to ``total_steps``, surviving trappable faults. Returns a
        summary dict (status ``completed`` or ``preempted``); raises
        :class:`RetryBudgetExceeded` when retries are spent."""
        attempt = 0
        self._preempt = False  # a prior preempted run() must not poison this one
        if self.mesh is not None:
            # gang semantics (docs/RESILIENCE.md multi-host): an in-process
            # retry would rejoin barriers its peers are not at — any fault
            # propagates out, and the RELAUNCHER restarts the whole mesh
            # with a fresh token
            return self._run_attempt(0)
        while True:
            try:
                return self._run_attempt(attempt)
            except UnsupportedExperimentError:
                raise  # a config error retries into the same wall — terminal
            except Exception as exc:  # worker fault — retry from the store
                attempt += 1
                self._c_faults.inc()
                TRACER.instant("resilience.fault", {
                    "attempt": attempt,
                    "error": f"{type(exc).__name__}: {exc}",
                })
                self.events.append({
                    "event": "fault", "attempt": attempt,
                    "error": f"{type(exc).__name__}: {exc}",
                })
                if attempt > self.sup.max_retries:
                    raise RetryBudgetExceeded(
                        f"retry budget ({self.sup.max_retries}) exhausted; "
                        f"last fault: {type(exc).__name__}: {exc}"
                    ) from exc
                delay = min(self.sup.backoff_max_s,
                            self.sup.backoff_base_s * 2 ** (attempt - 1))
                self.retry_delays.append(delay)
                self.events.append({"event": "retry", "attempt": attempt,
                                    "backoff_s": delay})
                logger.warning("worker fault (%s) — retry %d/%d after %.2fs",
                               exc, attempt, self.sup.max_retries, delay)
                self._sleep(delay)

    def _run_attempt(self, attempt: int) -> dict:
        t0 = time.perf_counter()
        exp = self._experiment_factory(self.exp_config)
        # the bit-exact contract requires the fused (step-keyed RNG) path:
        # the phased path draws z/ε from host-side sequential RNGs that
        # restart from the seed in every relaunched process, so a resumed
        # run would silently diverge from an uninterrupted one
        if getattr(exp, "_fused", True) is None:
            raise UnsupportedExperimentError(
                "this experiment config trains on the phased "
                "(parameter-averaging) path, whose host-side sequential RNG "
                "draws cannot be reconstructed from the step counter — "
                "bit-exact resume is impossible; use a fused-path config "
                "(distributed='none' or 'pmean')"
            )
        if self.mesh is not None:
            # ONE restore decision for the gang: worker 0 walks
            # latest_valid() (performing any quarantines exactly once) and
            # the peers load its published choice — N concurrent quarantine
            # renames racing each other's digest walks would be chaos
            generation = self.mesh.resolve_restore(self.store, attempt)
        else:
            generation = self.store.latest_valid()
        if generation is not None:
            with TRACER.span("resilience.restore", gen=generation.number,
                             attempt=attempt):
                exp.load_models(directory=generation.path)
            self._c_restores.inc()
            self.events.append({
                "event": "restore", "generation": generation.number,
                "step": exp.batch_counter, "attempt": attempt,
            })
        restore_s = time.perf_counter() - t0
        start_step = exp.batch_counter
        last_publish_step = exp.batch_counter if generation is not None else -1
        train_s = publish_s = 0.0
        publish_count = 0
        first_step_s: Optional[float] = None
        final_publish: Optional[dict] = None
        if generation is not None:
            # if nothing remains to train, the restored generation IS final
            final_publish = {
                "generation": generation.number, "seconds": 0.0,
                "digests": generation.manifest.get("state_digests"),
            }

        def publish() -> None:
            nonlocal publish_s, publish_count, last_publish_step, final_publish
            if exp.batch_counter == last_publish_step:
                return  # this boundary already holds a generation
            info = self._publish(exp)
            publish_s += info["seconds"]
            publish_count += 1
            last_publish_step = exp.batch_counter
            final_publish = info

        # serve-bundle cadence (deploy/ reload plane), attempt-local dedup
        # like the checkpoint cadence above
        serve = {"count": 0, "generation": None, "last_step": -1}

        def serve_publish() -> None:
            if (self.serve_store is None
                    or exp.batch_counter == serve["last_step"]):
                return
            if self.mesh is not None and not self.mesh.is_coordinator:
                return  # one serving bundle per mesh, from worker 0
            info = self._publish_serving(exp)
            serve["count"] += 1
            serve["generation"] = info.get("generation")
            serve["last_step"] = exp.batch_counter

        t_segment = time.perf_counter()

        def segment_span(status: str) -> None:
            TRACER.complete(
                "resilience.segment", t_segment, time.perf_counter(),
                {"attempt": attempt, "start_step": start_step,
                 "end_step": exp.batch_counter, "status": status})

        while exp.batch_counter < self.sup.total_steps:
            if self._preempt:
                if self.mesh is None:
                    publish()
                    serve_publish()  # a preempted trainer leaves its newest
                    # weights for the fleet, not just for its own resume
                # mesh mode: a preemption publish would need every worker
                # to reach this exact step — but SIGTERM lands mid-skew, so
                # the gang exits WITHOUT a boundary publish and resumes
                # from the last coordinated generation (≤ publish_every
                # steps old, the same bound a hard kill already has)
                segment_span("preempted")
                return self._summary(
                    "preempted", exp, attempt, start_step, restore_s,
                    first_step_s, train_s, publish_s, publish_count,
                    final_publish, serve)
            if self.faults is not None:
                self.faults.on_step(exp.batch_counter)
            feats, labels = self.batch_at(exp.batch_counter)
            t_wall = time.time()
            t = time.perf_counter()
            exp.train_iteration(feats, labels)
            t_end = time.perf_counter()
            train_s += t_end - t
            self._timeline.append({
                "phase": "step", "step": exp.batch_counter,
                "start_unix_s": round(t_wall, 6),
                "seconds": round(t_end - t, 6),
            })
            if TRACER.enabled:  # don't build per-step args when off
                TRACER.complete(
                    "resilience.step", t, t_end,
                    {"step": exp.batch_counter, "attempt": attempt})
            self._c_steps.inc()
            if first_step_s is None:
                first_step_s = time.perf_counter() - t0
            exp.batch_counter += 1
            if exp.batch_counter % self.sup.publish_every == 0:
                publish()
            if exp.batch_counter % self._serve_every == 0:
                serve_publish()
        publish()  # final state, even off-cadence
        serve_publish()  # the live fleet converges to the final weights
        segment_span("completed")
        return self._summary("completed", exp, attempt, start_step,
                             restore_s, first_step_s, train_s, publish_s,
                             publish_count, final_publish, serve)

    def _summary(self, status, exp, attempt, start_step, restore_s,
                 first_step_s, train_s, publish_s, publish_count,
                 final_publish, serve=None) -> dict:
        return {
            "status": status,
            "steps": exp.batch_counter,
            "total_steps": self.sup.total_steps,
            "start_step": start_step,
            "attempts_used": attempt,
            "retry_delays": list(self.retry_delays),
            "restore_s": restore_s,
            "time_to_first_step_s": first_step_s,
            "train_s": train_s,
            "publish_s": publish_s,
            "publish_count": publish_count,
            "final_generation": (final_publish or {}).get("generation"),
            "state_digests": (final_publish or {}).get("digests"),
            "serve_publish_count": (serve or {}).get("count", 0),
            "final_serve_generation": (serve or {}).get("generation"),
            "updater_shard": self._last_shard,
            # per-phase step/publish timeline on the wall clock — mesh
            # workers' summaries line up into one cross-worker story
            # (worker identity travels alongside, in the CLI's summary
            # envelope); bounded to the newest 4096 records
            "step_timeline": list(self._timeline),
            "worker": getattr(self.mesh, "worker", None),
            "world_size": getattr(self.mesh, "world_size", None),
            "events": list(self.events),
        }
