"""MNIST data prep — the gan.ipynb cell-2 analog (SURVEY §2.1 I19).

The reference's notebook downloads MNIST via Keras, scales to [0,1] float32,
flattens to 784, and writes ``mnist_train.csv`` / ``mnist_test.csv`` as
``%.2f`` CSV with the integer label appended as column 785, plus a stratified
100-per-class ``sampled_mnist_train.csv``. This module reproduces that file
contract and adds a deterministic synthetic MNIST-like source for offline
environments (this image has no network egress and no MNIST on disk), so
tests and benches run anywhere; real CSVs in the reference's format are
consumed transparently by the same loaders.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

IMAGE_SIDE = 28
NUM_FEATURES = IMAGE_SIDE * IMAGE_SIDE  # 784 (dl4jGANComputerVision.java:71)
NUM_CLASSES = 10


def _class_templates(seed: int) -> np.ndarray:
    """Ten smooth, well-separated 28×28 glyph templates. Each class is a
    low-frequency random field (sum of seeded 2-D cosines) — smooth like pen
    strokes, distinct across classes, so convnets have real signal to learn."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:IMAGE_SIDE, 0:IMAGE_SIDE].astype(np.float32) / IMAGE_SIDE
    templates = np.zeros((NUM_CLASSES, IMAGE_SIDE, IMAGE_SIDE), dtype=np.float32)
    for c in range(NUM_CLASSES):
        field = np.zeros((IMAGE_SIDE, IMAGE_SIDE), dtype=np.float32)
        for _ in range(6):
            fx, fy = rng.uniform(0.5, 3.0, size=2)
            px, py = rng.uniform(0, 2 * np.pi, size=2)
            amp = rng.uniform(0.4, 1.0)
            field += amp * np.cos(2 * np.pi * fx * xx + px) * np.cos(
                2 * np.pi * fy * yy + py
            )
        field = (field - field.min()) / (field.max() - field.min() + 1e-8)
        # soft vignette keeps mass centered like handwritten digits
        r2 = (xx - 0.5) ** 2 + (yy - 0.5) ** 2
        templates[c] = field * np.exp(-4.0 * r2)
    return templates


def synthetic_mnist(
    num_train: int = 2000,
    num_test: int = 500,
    seed: int = 666,
    noise: float = 0.08,
    max_shift: int = 2,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """Deterministic MNIST-shaped dataset: ((x_train, y_train), (x_test, y_test))
    with x float32 in [0,1] of shape (N, 784) and y int labels — the exact
    contract of ``mnist.load_data()`` post-processing in gan.ipynb cell 2."""
    templates = _class_templates(seed)
    rng = np.random.default_rng(seed + 1)

    def make(n: int) -> Tuple[np.ndarray, np.ndarray]:
        labels = rng.integers(0, NUM_CLASSES, size=n)
        imgs = templates[labels].copy()
        shifts = rng.integers(-max_shift, max_shift + 1, size=(n, 2))
        for i in range(n):
            imgs[i] = np.roll(imgs[i], shifts[i], axis=(0, 1))
        imgs += rng.normal(0.0, noise, size=imgs.shape).astype(np.float32)
        imgs = np.clip(imgs, 0.0, 1.0)
        return imgs.reshape(n, NUM_FEATURES).astype(np.float32), labels.astype(np.int64)

    return make(num_train), make(num_test)


def write_mnist_csv(
    path: str, features: np.ndarray, labels: np.ndarray, fmt: str = "%.2f"
) -> str:
    """Write the reference CSV layout: 784 feature columns then the label as
    column 785, ``%.2f`` formatted (gan.ipynb cell 2's np.savetxt calls)."""
    features = np.asarray(features, dtype=np.float32).reshape(len(labels), -1)
    table = np.concatenate(
        [features, np.asarray(labels, dtype=np.float32).reshape(-1, 1)], axis=1
    )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # stays on np.savetxt deliberately: prepared datasets must be byte-stable
    # across machines, and the native writer's tie-rounding (half-away-from-
    # zero) differs from printf's at exact halves. The hot export paths use
    # the native writer; one-time data prep does not need it.
    np.savetxt(path, table, delimiter=",", fmt=fmt)
    return path


def stratified_sample(
    features: np.ndarray, labels: np.ndarray, per_class: int = 100, seed: int = 666
) -> Tuple[np.ndarray, np.ndarray]:
    """The notebook's 100-per-class ``sampled_mnist_train.csv`` subset."""
    rng = np.random.default_rng(seed)
    keep = []
    for c in np.unique(labels):
        idx = np.flatnonzero(labels == c)
        take = min(per_class, idx.size)
        keep.append(rng.choice(idx, size=take, replace=False))
    keep = np.concatenate(keep)
    rng.shuffle(keep)
    return features[keep], labels[keep]


def load_mnist_csv(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Read a reference-format CSV back into (features[N,784] float32 in [0,1],
    labels[N] int64)."""
    from gan_deeplearning4j_tpu.data.records import CSVRecordReader, FileSplit

    reader = CSVRecordReader(0, ",")
    reader.initialize(FileSplit(path))
    data = reader.data
    return data[:, :NUM_FEATURES].astype(np.float32), data[:, NUM_FEATURES].astype(np.int64)


def prepare_mnist(
    out_dir: str,
    num_train: int = 2000,
    num_test: int = 500,
    seed: int = 666,
    source: Optional[str] = None,
    prefix: str = "mnist",
) -> Tuple[str, str]:
    """End-to-end cell-2 analog: obtain MNIST (real CSVs under ``source`` if
    present, else synthetic), write ``{prefix}_train.csv`` + ``{prefix}_test.csv``
    (+ the stratified sample) under ``out_dir``; returns the two paths."""
    train_path = os.path.join(out_dir, f"{prefix}_train.csv")
    test_path = os.path.join(out_dir, f"{prefix}_test.csv")
    if source is not None:
        src_train = os.path.join(source, f"{prefix}_train.csv")
        src_test = os.path.join(source, f"{prefix}_test.csv")
        if os.path.exists(src_train) and os.path.exists(src_test):
            xtr, ytr = load_mnist_csv(src_train)
            xte, yte = load_mnist_csv(src_test)
        else:
            raise FileNotFoundError(f"no mnist CSVs under {source!r}")
    else:
        (xtr, ytr), (xte, yte) = synthetic_mnist(num_train, num_test, seed)
    write_mnist_csv(train_path, xtr, ytr)
    write_mnist_csv(test_path, xte, yte)
    xs, ys = stratified_sample(xtr, ytr, per_class=100, seed=seed)
    write_mnist_csv(os.path.join(out_dir, f"sampled_{prefix}_train.csv"), xs, ys)
    return train_path, test_path
