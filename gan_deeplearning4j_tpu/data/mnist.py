"""MNIST data prep — the gan.ipynb cell-2 analog (SURVEY §2.1 I19).

The reference's notebook downloads MNIST via Keras, scales to [0,1] float32,
flattens to 784, and writes ``mnist_train.csv`` / ``mnist_test.csv`` as
``%.2f`` CSV with the integer label appended as column 785, plus a stratified
100-per-class ``sampled_mnist_train.csv``. This module reproduces that file
contract and adds a deterministic synthetic MNIST-like source for offline
environments (this image has no network egress and no MNIST on disk), so
tests and benches run anywhere; real CSVs in the reference's format are
consumed transparently by the same loaders.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

IMAGE_SIDE = 28
NUM_FEATURES = IMAGE_SIDE * IMAGE_SIDE  # 784 (dl4jGANComputerVision.java:71)
NUM_CLASSES = 10


def _class_templates(seed: int) -> np.ndarray:
    """Ten smooth, well-separated 28×28 glyph templates. Each class is a
    low-frequency random field (sum of seeded 2-D cosines) — smooth like pen
    strokes, distinct across classes, so convnets have real signal to learn."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:IMAGE_SIDE, 0:IMAGE_SIDE].astype(np.float32) / IMAGE_SIDE
    templates = np.zeros((NUM_CLASSES, IMAGE_SIDE, IMAGE_SIDE), dtype=np.float32)
    for c in range(NUM_CLASSES):
        field = np.zeros((IMAGE_SIDE, IMAGE_SIDE), dtype=np.float32)
        for _ in range(6):
            fx, fy = rng.uniform(0.5, 3.0, size=2)
            px, py = rng.uniform(0, 2 * np.pi, size=2)
            amp = rng.uniform(0.4, 1.0)
            field += amp * np.cos(2 * np.pi * fx * xx + px) * np.cos(
                2 * np.pi * fy * yy + py
            )
        field = (field - field.min()) / (field.max() - field.min() + 1e-8)
        # soft vignette keeps mass centered like handwritten digits
        r2 = (xx - 0.5) ** 2 + (yy - 0.5) ** 2
        templates[c] = field * np.exp(-4.0 * r2)
    return templates


def synthetic_mnist(
    num_train: int = 2000,
    num_test: int = 500,
    seed: int = 666,
    noise: float = 0.08,
    max_shift: int = 2,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """Deterministic MNIST-shaped dataset: ((x_train, y_train), (x_test, y_test))
    with x float32 in [0,1] of shape (N, 784) and y int labels — the exact
    contract of ``mnist.load_data()`` post-processing in gan.ipynb cell 2."""
    templates = _class_templates(seed)
    rng = np.random.default_rng(seed + 1)

    def make(n: int) -> Tuple[np.ndarray, np.ndarray]:
        labels = rng.integers(0, NUM_CLASSES, size=n)
        imgs = templates[labels].copy()
        shifts = rng.integers(-max_shift, max_shift + 1, size=(n, 2))
        for i in range(n):
            imgs[i] = np.roll(imgs[i], shifts[i], axis=(0, 1))
        imgs += rng.normal(0.0, noise, size=imgs.shape).astype(np.float32)
        imgs = np.clip(imgs, 0.0, 1.0)
        return imgs.reshape(n, NUM_FEATURES).astype(np.float32), labels.astype(np.int64)

    return make(num_train), make(num_test)


# -- IDX (the real MNIST distribution format) --------------------------------

_IDX_DTYPES = {
    0x08: np.dtype(np.uint8), 0x09: np.dtype(np.int8), 0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"), 0x0D: np.dtype(">f4"), 0x0E: np.dtype(">f8"),
}

_IDX_NAMES = {
    "train_images": ("train-images-idx3-ubyte", "train-images.idx3-ubyte"),
    "train_labels": ("train-labels-idx1-ubyte", "train-labels.idx1-ubyte"),
    "test_images": ("t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"),
    "test_labels": ("t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"),
}


def read_idx(path: str) -> np.ndarray:
    """Read one IDX-format array (the format of the canonical MNIST files;
    yann.lecun.com spec: 2 zero bytes, dtype code, ndim, big-endian dims,
    then row-major data). ``.gz`` paths are decompressed transparently."""
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as fh:
        raw = fh.read()
    if len(raw) < 4 or raw[0] != 0 or raw[1] != 0:
        raise ValueError(f"{path}: not an IDX file (bad magic {raw[:4]!r})")
    if raw[2] not in _IDX_DTYPES:
        raise ValueError(f"{path}: unknown IDX dtype code 0x{raw[2]:02x}")
    dtype, ndim = _IDX_DTYPES[raw[2]], raw[3]
    dims = np.frombuffer(raw, ">i4", count=ndim, offset=4)
    expected = 4 + 4 * ndim + int(np.prod(dims)) * dtype.itemsize
    if len(raw) < expected:
        raise ValueError(f"{path}: truncated IDX file ({len(raw)} < {expected} bytes)")
    return np.frombuffer(raw, dtype, count=int(np.prod(dims)),
                         offset=4 + 4 * ndim).reshape(dims)


def _find_idx_file(directory: str, names: Tuple[str, ...]) -> Optional[str]:
    for name in names:
        for candidate in (name, name + ".gz"):
            path = os.path.join(directory, candidate)
            if os.path.exists(path):
                return path
    return None


def find_mnist_idx(extra_dirs: Tuple[str, ...] = ()) -> Optional[str]:
    """Locate a directory holding the four canonical MNIST IDX files.
    Searched: ``$MNIST_DIR``, any ``extra_dirs``, then the usual dataset
    caches. Returns the directory or None (this image ships none — verified
    round 2 — but real deployments drop the files in and they win)."""
    candidates = []
    if os.environ.get("MNIST_DIR"):
        candidates.append(os.environ["MNIST_DIR"])
    candidates.extend(extra_dirs)
    home = os.path.expanduser("~")
    candidates += [
        os.path.join(home, ".keras", "datasets"),
        os.path.join(home, ".keras", "datasets", "mnist"),
        os.path.join(home, "data", "mnist"),
        "/data/mnist", "/datasets/mnist", "/data", "/datasets",
    ]
    for d in candidates:
        if d and os.path.isdir(d) and all(
            _find_idx_file(d, names) for names in _IDX_NAMES.values()
        ):
            return d
    return None


def load_mnist_idx(directory: str) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """Load real MNIST from IDX files: ((x_train, y_train), (x_test, y_test)),
    x float32 (N, 784) in [0,1] — the exact gan.ipynb cell-2 post-processing
    (scale /255, flatten)."""
    arrays = {}
    for key, names in _IDX_NAMES.items():
        path = _find_idx_file(directory, names)
        if path is None:
            raise FileNotFoundError(f"missing MNIST IDX file {names[0]}[.gz] in {directory!r}")
        arrays[key] = read_idx(path)

    def prep(images, labels):
        x = images.astype(np.float32).reshape(len(images), -1) / 255.0
        return x, labels.astype(np.int64)

    return (
        prep(arrays["train_images"], arrays["train_labels"]),
        prep(arrays["test_images"], arrays["test_labels"]),
    )


# -- real handwritten digits without egress ----------------------------------

def _resize_bilinear(imgs: np.ndarray, side: int) -> np.ndarray:
    """(N, h, w) → (N, side, side) bilinear, align-corners=False convention."""
    n, h, w = imgs.shape
    ys = (np.arange(side) + 0.5) * h / side - 0.5
    xs = (np.arange(side) + 0.5) * w / side - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0).astype(np.float32)[None, :, None]
    wx = np.clip(xs - x0, 0.0, 1.0).astype(np.float32)[None, None, :]
    a = imgs[:, y0][:, :, x0]
    b = imgs[:, y0][:, :, x1]
    c = imgs[:, y1][:, :, x0]
    d = imgs[:, y1][:, :, x1]
    top = a * (1.0 - wx) + b * wx
    bot = c * (1.0 - wx) + d * wx
    return (top * (1.0 - wy) + bot * wy).astype(np.float32)


def real_digits(
    num_train: int = 2000,
    num_test: int = 500,
    seed: int = 666,
    max_shift: int = 2,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """REAL handwritten digits without network egress: scikit-learn's bundled
    UCI optdigits set (1797 genuine 8×8 handwritten digits), bilinearly
    upsampled to 28×28 and shift-augmented up to the requested sizes. Not
    MNIST, but real pen strokes — the closest this image gets to gan.ipynb
    cell 2's ``mnist.load_data()`` (no MNIST exists on this disk and there is
    no egress; see ``find_mnist_idx``). Raises ImportError without sklearn."""
    from sklearn.datasets import load_digits

    d = load_digits()
    imgs = _resize_bilinear(d.images.astype(np.float32) / 16.0, IMAGE_SIDE)
    imgs = np.clip(imgs, 0.0, 1.0)
    labels = d.target.astype(np.int64)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(imgs))
    imgs, labels = imgs[perm], labels[perm]
    n_test_src = max(1, min(len(imgs) // 4, num_test))
    src = {
        "train": (imgs[n_test_src:], labels[n_test_src:]),
        "test": (imgs[:n_test_src], labels[:n_test_src]),
    }

    def take(split: str, n: int) -> Tuple[np.ndarray, np.ndarray]:
        base_x, base_y = src[split]
        idx = rng.integers(0, len(base_x), size=n) if n > len(base_x) else \
            rng.permutation(len(base_x))[:n]
        x, y = base_x[idx].copy(), base_y[idx]
        shifts = rng.integers(-max_shift, max_shift + 1, size=(n, 2))
        for i in range(n):
            x[i] = np.roll(x[i], shifts[i], axis=(0, 1))
        return x.reshape(n, NUM_FEATURES).astype(np.float32), y

    return take("train", num_train), take("test", num_test)


def load_mnist(
    num_train: int = 2000,
    num_test: int = 500,
    seed: int = 666,
    data_dir: Optional[str] = None,
) -> Tuple[str, Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]]:
    """Best-available MNIST-shaped data: real IDX MNIST if on disk, else the
    real (non-MNIST) UCI digits, else the synthetic glyphs. Returns
    (provenance_tag, ((x_train, y_train), (x_test, y_test)))."""
    idx_dir = find_mnist_idx((data_dir,) if data_dir else ())
    if idx_dir is not None:
        (xtr, ytr), (xte, yte) = load_mnist_idx(idx_dir)
        rng = np.random.default_rng(seed)
        tr = rng.permutation(len(xtr))[:num_train]
        te = rng.permutation(len(xte))[:num_test]
        return "mnist-idx", ((xtr[tr], ytr[tr]), (xte[te], yte[te]))
    try:
        return "uci-digits-upsampled", real_digits(num_train, num_test, seed)
    except ImportError:
        return "synthetic", synthetic_mnist(num_train, num_test, seed)


def write_mnist_csv(
    path: str, features: np.ndarray, labels: np.ndarray, fmt: str = "%.2f"
) -> str:
    """Write the reference CSV layout: 784 feature columns then the label as
    column 785, ``%.2f`` formatted (gan.ipynb cell 2's np.savetxt calls)."""
    features = np.asarray(features, dtype=np.float32).reshape(len(labels), -1)
    table = np.concatenate(
        [features, np.asarray(labels, dtype=np.float32).reshape(-1, 1)], axis=1
    )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # stays on np.savetxt deliberately: prepared datasets must be byte-stable
    # across machines, and the native writer's tie-rounding (half-away-from-
    # zero) differs from printf's at exact halves. The hot export paths use
    # the native writer; one-time data prep does not need it.
    np.savetxt(path, table, delimiter=",", fmt=fmt)
    return path


def stratified_sample(
    features: np.ndarray, labels: np.ndarray, per_class: int = 100, seed: int = 666
) -> Tuple[np.ndarray, np.ndarray]:
    """The notebook's 100-per-class ``sampled_mnist_train.csv`` subset."""
    rng = np.random.default_rng(seed)
    keep = []
    for c in np.unique(labels):
        idx = np.flatnonzero(labels == c)
        take = min(per_class, idx.size)
        keep.append(rng.choice(idx, size=take, replace=False))
    keep = np.concatenate(keep)
    rng.shuffle(keep)
    return features[keep], labels[keep]


def load_mnist_csv(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Read a reference-format CSV back into (features[N,784] float32 in [0,1],
    labels[N] int64)."""
    from gan_deeplearning4j_tpu.data.records import CSVRecordReader, FileSplit

    reader = CSVRecordReader(0, ",")
    reader.initialize(FileSplit(path))
    data = reader.data
    return data[:, :NUM_FEATURES].astype(np.float32), data[:, NUM_FEATURES].astype(np.int64)


def prepare_mnist(
    out_dir: str,
    num_train: int = 2000,
    num_test: int = 500,
    seed: int = 666,
    source: Optional[str] = None,
    prefix: str = "mnist",
) -> Tuple[str, str]:
    """End-to-end cell-2 analog: obtain MNIST, write ``{prefix}_train.csv`` +
    ``{prefix}_test.csv`` (+ the stratified sample) under ``out_dir``;
    returns the two paths. ``source``: None → best available (IDX MNIST on
    disk > bundled real UCI digits > synthetic; see ``load_mnist``);
    ``"synthetic"`` → force the deterministic glyphs; a directory → read
    reference-format CSVs from it."""
    train_path = os.path.join(out_dir, f"{prefix}_train.csv")
    test_path = os.path.join(out_dir, f"{prefix}_test.csv")
    if source is not None and source != "synthetic":
        src_train = os.path.join(source, f"{prefix}_train.csv")
        src_test = os.path.join(source, f"{prefix}_test.csv")
        if os.path.exists(src_train) and os.path.exists(src_test):
            xtr, ytr = load_mnist_csv(src_train)
            xte, yte = load_mnist_csv(src_test)
        else:
            raise FileNotFoundError(f"no mnist CSVs under {source!r}")
    elif source == "synthetic":
        (xtr, ytr), (xte, yte) = synthetic_mnist(num_train, num_test, seed)
    else:
        _, ((xtr, ytr), (xte, yte)) = load_mnist(num_train, num_test, seed)
    write_mnist_csv(train_path, xtr, ytr)
    write_mnist_csv(test_path, xte, yte)
    xs, ys = stratified_sample(xtr, ytr, per_class=100, seed=seed)
    write_mnist_csv(os.path.join(out_dir, f"sampled_{prefix}_train.csv"), xs, ys)
    return train_path, test_path
