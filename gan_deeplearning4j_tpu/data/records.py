"""Record readers — the DataVec surface the reference exercises (D13).

Reference binding: ``CSVRecordReader(0, ",")`` over a
``FileSplit(ClassPathResource("mnist_train.csv").getFile())``
(dl4jGANComputerVision.java:372-377,395-400). Here a record reader yields
numpy float32 rows; the iterator layer batches and labelizes them.

The CSV path prefers the native C++ parser (``gan_deeplearning4j_tpu.native``)
when its shared library has been built — the TPU-native stand-in for DataVec's
JVM parsing — and falls back to numpy otherwise. Either way parsing happens
once per file; batching reuses the materialized matrix.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence

import numpy as np


class ClassPathResource:
    """Resolve a data file by name against a search path (DL4J's
    ``ClassPathResource`` resolved resources from the JVM classpath; here the
    search path is ``GAN_DL4J_TPU_DATA`` + explicit roots + CWD)."""

    def __init__(self, name: str, roots: Optional[Sequence[str]] = None):
        self.name = name
        env_root = os.environ.get("GAN_DL4J_TPU_DATA")
        self.roots: List[str] = list(roots or [])
        if env_root:
            self.roots.append(env_root)
        self.roots.extend([os.getcwd(), os.path.join(os.getcwd(), "resources")])

    def get_file(self) -> str:
        if os.path.isabs(self.name) and os.path.exists(self.name):
            return self.name
        for root in self.roots:
            candidate = os.path.join(root, self.name)
            if os.path.exists(candidate):
                return candidate
        raise FileNotFoundError(
            f"resource {self.name!r} not found under {self.roots}"
        )


class FileSplit:
    """Trivial split over one file/path (DL4J ``FileSplit``)."""

    def __init__(self, path):
        self.path = path if isinstance(path, str) else path.get_file()


class RecordReader:
    """Iteration protocol shared by all readers: ``has_next`` / ``next_record``
    / ``reset`` (DL4J RecordReader)."""

    def initialize(self, split: FileSplit) -> None:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_record(self) -> np.ndarray:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[np.ndarray]:
        self.reset()
        while self.has_next():
            yield self.next_record()


def _parse_csv(path: str, skip_lines: int, delimiter: str) -> np.ndarray:
    """Parse a numeric CSV to float32, preferring the native C++ parser."""
    try:
        from gan_deeplearning4j_tpu.native import csv_loader

        if csv_loader.available():
            return csv_loader.load_csv(path, skip_lines=skip_lines, delimiter=delimiter)
    except ImportError:
        pass
    return np.loadtxt(
        path, delimiter=delimiter, skiprows=skip_lines, dtype=np.float32, ndmin=2
    )


def write_csv(
    path: str, array: np.ndarray, precision: int = 6, delimiter: str = ","
) -> str:
    """Write a float matrix as fixed-precision CSV, preferring the native C++
    writer (the reference's export hot path :550-598 without per-scalar IO)."""
    try:
        from gan_deeplearning4j_tpu.native import csv_loader

        if csv_loader.available():
            return csv_loader.write_csv(path, array, delimiter=delimiter, precision=precision)
    except ImportError:
        pass
    np.savetxt(path, np.asarray(array), delimiter=delimiter, fmt=f"%.{precision}f")
    return path


class CSVRecordReader(RecordReader):
    """``CSVRecordReader(skipLines, delimiter)`` analog. The whole file is
    parsed to one float32 matrix up front (the reference re-reads per record
    through the JVM; one parse + slicing is the device-friendly shape)."""

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self._data: Optional[np.ndarray] = None
        self._cursor = 0

    def initialize(self, split: FileSplit) -> None:
        self._data = _parse_csv(split.path, self.skip_lines, self.delimiter)
        self._cursor = 0

    @property
    def data(self) -> np.ndarray:
        if self._data is None:
            raise RuntimeError("CSVRecordReader not initialized (call initialize)")
        return self._data

    def has_next(self) -> bool:
        return self._cursor < self.data.shape[0]

    def next_record(self) -> np.ndarray:
        row = self.data[self._cursor]
        self._cursor += 1
        return row

    def next_block(self, n: int) -> np.ndarray:
        """Batched read — n rows at once (the device-friendly access path)."""
        block = self.data[self._cursor : self._cursor + n]
        self._cursor += block.shape[0]
        return block

    def remaining(self) -> int:
        return self.data.shape[0] - self._cursor

    def reset(self) -> None:
        self._cursor = 0


class InMemoryRecordReader(RecordReader):
    """Reader over an in-memory matrix (tests / synthetic data)."""

    def __init__(self, data: np.ndarray):
        self._data = np.asarray(data, dtype=np.float32)
        self._cursor = 0

    def initialize(self, split: Optional[FileSplit] = None) -> None:
        self._cursor = 0

    @property
    def data(self) -> np.ndarray:
        return self._data

    def has_next(self) -> bool:
        return self._cursor < self._data.shape[0]

    def next_record(self) -> np.ndarray:
        row = self._data[self._cursor]
        self._cursor += 1
        return row

    def next_block(self, n: int) -> np.ndarray:
        block = self._data[self._cursor : self._cursor + n]
        self._cursor += block.shape[0]
        return block

    def remaining(self) -> int:
        return self._data.shape[0] - self._cursor

    def reset(self) -> None:
        self._cursor = 0
