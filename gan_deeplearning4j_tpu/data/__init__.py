"""Data layer — TPU-native DataVec equivalent (SURVEY §2.2 D13-D14, layer H).

The reference's pipeline is CSV → ``CSVRecordReader`` → ``FileSplit`` →
``RecordReaderDataSetIterator(batch, labelIndex=784, numClasses=10)`` →
``DataSet{features, one-hot labels}`` (dl4jGANComputerVision.java:372-377,
395-400). This package provides the same capability surface with device
residency as the design goal: batches land in TPU HBM once and stay there.
"""

from gan_deeplearning4j_tpu.data.dataset import DataSet
from gan_deeplearning4j_tpu.data.records import (
    ClassPathResource,
    CSVRecordReader,
    FileSplit,
    write_csv,
    InMemoryRecordReader,
)
from gan_deeplearning4j_tpu.data.iterator import (
    ArrayDataSetIterator,
    DataSetIterator,
    DevicePrefetchIterator,
    DeviceResidentIterator,
    RecordReaderDataSetIterator,
)
from gan_deeplearning4j_tpu.data.mnist import (
    load_mnist_csv,
    synthetic_mnist,
    write_mnist_csv,
)

__all__ = [
    "DataSet",
    "ClassPathResource",
    "CSVRecordReader",
    "FileSplit",
    "write_csv",
    "InMemoryRecordReader",
    "ArrayDataSetIterator",
    "DataSetIterator",
    "DevicePrefetchIterator",
    "DeviceResidentIterator",
    "RecordReaderDataSetIterator",
    "load_mnist_csv",
    "synthetic_mnist",
    "write_mnist_csv",
]
