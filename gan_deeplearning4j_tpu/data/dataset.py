"""DataSet — the (features, labels) batch value type (SURVEY §2.2 D14).

The reference ships ``org.nd4j.linalg.dataset.DataSet`` objects between Spark
workers with Kryo serialization (dl4jGANComputerVision.java:320-321,414-421).
On TPU there is one process and batches are jax Arrays, so the serialization
concern disappears; DataSet remains as the typed batch struct the trainer and
iterators exchange. It is registered as a pytree so it can cross jit/shard_map
boundaries and be sharded over the mesh ``data`` axis directly.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


class DataSet:
    """A batch of ``features`` and (optionally one-hot) ``labels``."""

    def __init__(self, features, labels=None):
        self.features = features
        self.labels = labels

    # -- DL4J surface -------------------------------------------------------
    def get_features(self):
        return self.features

    def get_labels(self):
        return self.labels

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def __len__(self) -> int:
        return self.num_examples()

    def __repr__(self) -> str:
        f = tuple(self.features.shape)
        l = tuple(self.labels.shape) if self.labels is not None else None
        return f"DataSet(features={f}, labels={l})"

    # -- assembly (the reference builds 2-element List<DataSet>, :414-421) ---
    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        """Row-concatenate several DataSets (Nd4j.vstack over a List<DataSet>)."""
        feats = jnp.concatenate([d.features for d in datasets], axis=0)
        if datasets[0].labels is None:
            return DataSet(feats)
        labels = jnp.concatenate([d.labels for d in datasets], axis=0)
        return DataSet(feats, labels)

    def to_device(self, sharding=None) -> "DataSet":
        """Place the batch in device HBM (optionally sharded over a mesh)."""
        put = (lambda x: jax.device_put(x, sharding)) if sharding is not None else jax.device_put
        labels = put(self.labels) if self.labels is not None else None
        return DataSet(put(self.features), labels)

    def shard_batch(self, n: int) -> "DataSet":
        """Check/truncate the batch to a multiple of ``n`` (mesh data-axis size)."""
        b = self.num_examples()
        usable = (b // n) * n
        if usable == 0:
            raise ValueError(f"batch of {b} cannot be split over {n} shards")
        if usable == b:
            return self
        return DataSet(self.features[:usable], None if self.labels is None else self.labels[:usable])


def one_hot(labels, num_classes: int, dtype=jnp.float32):
    """Integer labels → one-hot rows (RecordReaderDataSetIterator's labelization)."""
    labels = jnp.asarray(labels).astype(jnp.int32).reshape(-1)
    return jax.nn.one_hot(labels, num_classes, dtype=dtype)


def one_hot_np(labels: np.ndarray, num_classes: int, dtype=np.float32) -> np.ndarray:
    """Host-side one-hot (used by iterators before device transfer)."""
    labels = np.asarray(labels).astype(np.int64).reshape(-1)
    out = np.zeros((labels.shape[0], num_classes), dtype=dtype)
    out[np.arange(labels.shape[0]), labels] = 1.0
    return out


def _flatten_dataset(d: DataSet):
    if d.labels is None:
        return (d.features,), (False,)
    return (d.features, d.labels), (True,)


def _unflatten_dataset(aux, children):
    (has_labels,) = aux
    if has_labels:
        return DataSet(children[0], children[1])
    return DataSet(children[0])


jax.tree_util.register_pytree_node(DataSet, _flatten_dataset, _unflatten_dataset)


def train_test_split(features, labels, test_fraction: float, seed: int = 666):
    """Deterministic host-side split helper (notebook-cell-2 style)."""
    n = features.shape[0]
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_test = int(round(n * test_fraction))
    test_idx, train_idx = perm[:n_test], perm[n_test:]
    return (
        (features[train_idx], labels[train_idx]),
        (features[test_idx], labels[test_idx]),
    )
