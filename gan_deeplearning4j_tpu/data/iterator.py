"""DataSet iterators — batching, labelization, reset, device prefetch (D13).

``RecordReaderDataSetIterator(reader, batch, labelIndex=784, numClasses=10)``
(dl4jGANComputerVision.java:374-377) turns CSV rows into
``DataSet{features(B,784), one-hot(B,10)}`` batches with ``hasNext/next/reset``.

TPU-first differences from the JVM original:
- batches are cut from one resident float32 matrix, not per-row boxing;
- ``DevicePrefetchIterator`` double-buffers: while the trainer consumes batch
  k, batch k+1's host→HBM transfer is already in flight (the north-star "no
  host round-trips per step"); with a mesh sharding it lands pre-sharded over
  the ``data`` axis, so the training step never sees a host array.
"""

from __future__ import annotations

from collections import deque
from typing import Iterator, Optional

import jax
import numpy as np

from gan_deeplearning4j_tpu.data.dataset import DataSet, one_hot_np
from gan_deeplearning4j_tpu.data.records import RecordReader


class DataSetIterator:
    """Iterator protocol (DL4J DataSetIterator): has_next / next / reset."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        while self.has_next():
            yield self.next()


class RecordReaderDataSetIterator(DataSetIterator):
    """Reference-parity iterator: rows → (features, one-hot labels) batches.

    ``label_index`` is the column holding the integer class (784 for the MNIST
    CSVs — features are columns [0, 784)); ``num_classes`` the one-hot width.
    ``label_index=None`` yields unlabeled feature batches.
    """

    def __init__(
        self,
        reader: RecordReader,
        batch_size: int,
        label_index: Optional[int] = None,
        num_classes: Optional[int] = None,
    ):
        if (label_index is None) != (num_classes is None):
            raise ValueError("label_index and num_classes must be given together")
        self.reader = reader
        self.batch_size = int(batch_size)
        self.label_index = label_index
        self.num_classes = num_classes

    def has_next(self) -> bool:
        return self.reader.has_next()

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        if hasattr(self.reader, "next_block"):
            block = self.reader.next_block(self.batch_size)
        else:
            rows = []
            while self.reader.has_next() and len(rows) < self.batch_size:
                rows.append(self.reader.next_record())
            block = np.stack(rows)
        return self._to_dataset(block)

    def _to_dataset(self, block: np.ndarray) -> DataSet:
        if self.label_index is None:
            return DataSet(jax.numpy.asarray(block))
        li = self.label_index
        features = np.concatenate([block[:, :li], block[:, li + 1 :]], axis=1)
        labels = one_hot_np(block[:, li], self.num_classes)
        return DataSet(jax.numpy.asarray(features), jax.numpy.asarray(labels))

    def reset(self) -> None:
        self.reader.reset()


class ArrayDataSetIterator(DataSetIterator):
    """Iterator over in-memory (features, labels) arrays — the assembled
    List<DataSet> → RDD path (dl4jGANComputerVision.java:414-425) without the
    serialization detour. Optional shuffling is seeded and re-derived per epoch."""

    def __init__(
        self,
        features: np.ndarray,
        labels: Optional[np.ndarray] = None,
        batch_size: int = 128,
        shuffle: bool = False,
        seed: int = 666,
        drop_remainder: bool = False,
    ):
        self.features = np.asarray(features, dtype=np.float32)
        self.labels = None if labels is None else np.asarray(labels, dtype=np.float32)
        if self.labels is not None and self.labels.shape[0] != self.features.shape[0]:
            raise ValueError("features/labels row mismatch")
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self._epoch = 0
        self._order = self._make_order()
        self._cursor = 0

    def _make_order(self) -> np.ndarray:
        n = self.features.shape[0]
        if not self.shuffle:
            return np.arange(n)
        rng = np.random.default_rng(self.seed + self._epoch)
        return rng.permutation(n)

    def has_next(self) -> bool:
        remaining = self.features.shape[0] - self._cursor
        if self.drop_remainder:
            return remaining >= self.batch_size
        return remaining > 0

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        idx = self._order[self._cursor : self._cursor + self.batch_size]
        self._cursor += len(idx)
        feats = jax.numpy.asarray(self.features[idx])
        if self.labels is None:
            return DataSet(feats)
        return DataSet(feats, jax.numpy.asarray(self.labels[idx]))

    def reset(self) -> None:
        self._epoch += 1
        self._order = self._make_order()
        self._cursor = 0


class DeviceResidentIterator(DataSetIterator):
    """Upload the WHOLE dataset to device HBM once; serve batches as
    device-side slices.

    For datasets that fit in HBM (MNIST-scale: tens of MB against ~16 GB),
    this removes the host→device link from the steady state entirely — the
    right call on a tunneled/remote accelerator, where re-uploading even an
    identical batch costs multiple milliseconds (measured round 3: numpy
    feeds were ~6x slower than resident batches at batch 64). Epoch order is
    sequential; pass ``shuffle=True`` for a seeded per-epoch permutation
    (host-side index draw, device-side ``take``).

    With a mesh ``sharding`` the resident arrays land sharded over the data
    axis; batch slices then reshard per step — prefer
    :class:`DevicePrefetchIterator` per-batch placement for multi-device
    meshes, this class for the single-chip hot path.
    """

    def __init__(
        self,
        features,
        labels=None,
        batch_size: int = 128,
        shuffle: bool = False,
        seed: int = 666,
        drop_remainder: bool = False,
        sharding=None,
    ):
        import jax.numpy as jnp

        put = (
            (lambda x: jax.device_put(np.asarray(x, np.float32), sharding))
            if sharding is not None
            else (lambda x: jnp.asarray(np.asarray(x, np.float32)))
        )
        self.features = put(features)
        self.labels = put(labels) if labels is not None else None
        if self.labels is not None and self.labels.shape[0] != self.features.shape[0]:
            raise ValueError("features/labels row mismatch")
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self._epoch = 0
        self._order = self._make_order()
        self._cursor = 0

    def _make_order(self):
        self._windowed = None  # (nb, B, …) views rebuilt lazily per epoch
        if not self.shuffle:
            return None  # sequential: pure device slicing, no gather
        rng = np.random.default_rng(self.seed + self._epoch)
        import jax.numpy as jnp

        return jnp.asarray(rng.permutation(self.features.shape[0]))

    def _window_arrays(self):
        """(nb, B, …) reshapes of the epoch's full batches, built with ONE
        device op per epoch — ``next_window`` then serves a k-batch window
        as a single slice instead of k per-batch dispatches (each dispatch
        costs ~1 ms host-side on a tunneled chip; measured round 3)."""
        if self._windowed is None:
            import jax.numpy as jnp

            b = self.batch_size
            nb = self.features.shape[0] // b
            feats = self.features
            labels = self.labels
            if self._order is not None:
                feats = jnp.take(feats, self._order, axis=0)
                labels = None if labels is None else jnp.take(labels, self._order, axis=0)
            self._windowed = (
                nb,
                feats[: nb * b].reshape((nb, b) + feats.shape[1:]),
                None
                if labels is None
                else labels[: nb * b].reshape((nb, b) + labels.shape[1:]),
            )
        return self._windowed

    def next_window(self, k: int):
        """Up to ``k`` consecutive full batches as ONE stacked (k', B, …)
        device slice — k' is the largest power of two ≤ min(k, remaining
        full batches), so callers compile a bounded set of window sizes.
        Returns None when fewer than one full aligned batch remains (the
        ragged tail and misaligned cursors fall back to ``next()``)."""
        if k < 1 or self._cursor % self.batch_size != 0:
            return None
        nb, wf, wl = self._window_arrays()
        at = self._cursor // self.batch_size
        avail = min(k, nb - at)
        if avail < 1:
            return None
        take = 1 << (avail.bit_length() - 1)
        self._cursor += take * self.batch_size
        return (
            wf[at : at + take],
            None if wl is None else wl[at : at + take],
        )

    def has_next(self) -> bool:
        remaining = self.features.shape[0] - self._cursor
        if self.drop_remainder:
            return remaining >= self.batch_size
        return remaining > 0

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        import jax.numpy as jnp

        lo = self._cursor
        hi = min(lo + self.batch_size, self.features.shape[0])
        self._cursor = hi
        if self._order is None:
            feats = self.features[lo:hi]
            labels = None if self.labels is None else self.labels[lo:hi]
        else:
            idx = self._order[lo:hi]
            feats = jnp.take(self.features, idx, axis=0)
            labels = None if self.labels is None else jnp.take(self.labels, idx, axis=0)
        return DataSet(feats, labels)

    def reset(self) -> None:
        self._epoch += 1
        self._order = self._make_order()
        self._cursor = 0


class DevicePrefetchIterator(DataSetIterator):
    """Wrap any DataSetIterator with ahead-of-time device placement.

    ``depth`` batches are transferred ahead with ``jax.device_put`` (async
    under PJRT: the copy overlaps the running step). Pass a
    ``NamedSharding(mesh, P("data"))`` to land batches pre-sharded across the
    mesh — the device-resident replacement for the reference's prefetch knob
    (``workerPrefetchNumBatches``, dl4jGANComputerVision.java:328).

    ``transform`` is an optional host-side per-batch hook
    (``DataSet -> DataSet``) applied BEFORE device placement —
    normalization/augmentation for the streaming-pipeline direction
    without touching the step loop. It runs during prefetch refills, i.e.
    inside whatever region is consuming the iterator: a transform that
    performs a host callback (``jax.debug.*``, ``io_callback``) poisons
    every timed window it refills under — jaxlint JG019 polices exactly
    that shape (docs/STATIC_ANALYSIS.md).
    """

    def __init__(self, inner: DataSetIterator, depth: int = 2, sharding=None,
                 transform=None):
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1")
        self.inner = inner
        self.depth = depth
        self.sharding = sharding
        self.transform = transform
        self._queue: deque = deque()

    def _fill(self) -> None:
        while len(self._queue) < self.depth and self.inner.has_next():
            batch = self.inner.next()
            if self.transform is not None:
                batch = self.transform(batch)
            self._queue.append(batch.to_device(self.sharding))

    def has_next(self) -> bool:
        self._fill()
        return len(self._queue) > 0

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        batch = self._queue.popleft()
        self._fill()  # keep the pipeline full while this batch is consumed
        return batch

    def reset(self) -> None:
        self._queue.clear()
        self.inner.reset()
