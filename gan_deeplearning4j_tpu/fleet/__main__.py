"""Fleet CLI — ``python -m gan_deeplearning4j_tpu.fleet [flags]``.

Boots the whole serving plane from one checkpoint store: N worker
processes (spawned from the newest digest-valid serving generation), the
health-ejecting router in front of them, and the manager's supervise +
rolling-upgrade loop. Runs until interrupted. Example::

    python -m gan_deeplearning4j_tpu.fleet --store bundles \\
        --workers 3 --port 8100 --canary-data canary.npz
"""

from __future__ import annotations

import argparse
import logging
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gan_deeplearning4j_tpu.fleet",
        description="multi-process serving fleet: router + N workers",
    )
    p.add_argument("--store", required=True,
                   help="checkpoint store root holding serving generations")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8100, help="router port")
    p.add_argument("--worker-ports", default=None,
                   help="comma-separated worker ports (default: free ports)")
    p.add_argument("--log-dir", default=".",
                   help="where worker-<id>.log files land")
    p.add_argument("--boot-wait", type=float, default=120.0,
                   help="seconds to wait for the first valid serving "
                        "generation in the store")
    p.add_argument("--poll", type=float, default=2.0,
                   help="store poll interval for rolling upgrades")
    p.add_argument("--request-timeout", type=float, default=10.0,
                   help="per-proxied-request timeout at the router")
    p.add_argument("--probe-interval", type=float, default=0.25,
                   help="health loop cadence (probes + /metrics scrapes)")
    p.add_argument("--retry-ratio", type=float, default=0.2,
                   help="retry-budget deposit per proxied request")
    p.add_argument("--retry-burst", type=float, default=10.0,
                   help="retry-budget token cap")
    p.add_argument("--eject-failures", type=int, default=3,
                   help="consecutive failures that eject a worker")
    p.add_argument("--reopen-after", type=float, default=1.0,
                   help="initial ejected→half-open backoff seconds")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="bounded wait for a draining worker's pipeline")
    p.add_argument("--warm-timeout", type=float, default=300.0,
                   help="bounded wait for a relaunched worker to go healthy")
    p.add_argument("--hang-restart", type=float, default=20.0,
                   help="force-restart a worker whose breaker stays open "
                        "this long while its process is alive")
    p.add_argument("--buckets", default=None,
                   help="worker batch ladder, e.g. 1,8,32,128")
    p.add_argument("--replicas", default=None,
                   help="device replicas per worker (int or 'all')")
    p.add_argument("--max-latency", type=float, default=None,
                   help="worker micro-batch trigger seconds")
    p.add_argument("--timeout", type=float, default=None,
                   help="worker default per-request deadline seconds")
    p.add_argument("--canary-data", default=None, metavar="NPZ",
                   help="npz with 'features' (and optionally 'labels') for "
                        "the fleet admission gate; omitted = digest-valid "
                        "generations roll ungated")
    p.add_argument("--canary-samples", type=int, default=256)
    p.add_argument("--canary-seed", type=int, default=666)
    p.add_argument("--canary-feature", choices=("raw", "dis_features"),
                   default="raw",
                   help="FID feature space for the admission probes "
                        "(dis_features: the incumbent classifier's feature "
                        "vertex — docs/FLEET.md)")
    p.add_argument("--canary-fid-ratio", type=float, default=1.5)
    p.add_argument("--canary-fid-slack", type=float, default=10.0)
    p.add_argument("--canary-acc-drop", type=float, default=0.05)
    p.add_argument("--telemetry", action="store_true",
                   help="enable span tracing on the router/manager process "
                        "AND every worker (GET /debug/trace then merges "
                        "one fleet-wide Chrome trace)")
    p.add_argument("--slo-availability", type=float, default=0.999,
                   help="availability target (fraction answered non-5xx)")
    p.add_argument("--slo-latency-ms", type=float, default=500.0,
                   help="latency objective threshold in milliseconds")
    p.add_argument("--slo-latency-target", type=float, default=0.99,
                   help="fraction of answers that must beat the threshold")
    p.add_argument("--slo-fast-window", type=float, default=60.0,
                   help="fast burn-rate window seconds")
    p.add_argument("--slo-slow-window", type=float, default=600.0,
                   help="slow burn-rate window seconds")
    p.add_argument("--autoscale", action="store_true",
                   help="enable the SLO-driven elastic control loop: "
                        "--workers becomes the min/boot size and the "
                        "fleet resizes up to --max-workers "
                        "(docs/FLEET.md 'Autoscaling')")
    p.add_argument("--max-workers", type=int, default=None,
                   help="autoscaler ceiling (default: 2x --workers); at "
                        "this size continued overload enters brownout")
    p.add_argument("--scale-up-pressure", type=float, default=3.0,
                   help="queue+in-flight per routable worker at/above "
                        "which a tick counts toward scale-up")
    p.add_argument("--scale-down-pressure", type=float, default=1.0,
                   help="pressure at/below which a tick counts toward "
                        "scale-down (must be < --scale-up-pressure)")
    p.add_argument("--scale-up-ticks", type=int, default=3,
                   help="consecutive overloaded ticks before scaling up")
    p.add_argument("--scale-down-ticks", type=int, default=10,
                   help="consecutive calm ticks before scaling down")
    p.add_argument("--scale-interval", type=float, default=1.0,
                   help="autoscaler decision tick seconds")
    p.add_argument("--scale-up-cooldown", type=float, default=5.0,
                   help="seconds after a scale-up before the next resize")
    p.add_argument("--scale-down-cooldown", type=float, default=15.0,
                   help="seconds after a scale-down before the next resize")
    p.add_argument("--brownout-max-rows", type=int, default=32,
                   help="tier-1 brownout: /v1/sample slabs with more rows "
                        "are shed with an honest 503")
    p.add_argument("--brownout-deadline-ms", type=float, default=1000.0,
                   help="tier-2 brownout: effective per-request deadline "
                        "cap injected at the router")
    p.add_argument("--brownout-exit-ticks", type=int, default=5,
                   help="consecutive calm ticks before a brownout tier "
                        "releases")
    p.add_argument("--spawn-backoff", type=float, default=0.5,
                   help="base seconds for the capped exponential backoff "
                        "on workers that die before becoming routable")
    p.add_argument("--spawn-backoff-max", type=float, default=30.0,
                   help="backoff cap for repeated spawn failures")
    p.add_argument("--compilation-cache", default=None, metavar="DIR",
                   help="shared persistent XLA compilation-cache dir "
                        "passed to every spawned worker: scale-ups, "
                        "draining restarts, and rolling upgrades reload "
                        "AOT artifacts instead of recompiling the ladder "
                        "(warm elasticity — docs/SERVING.md)")
    p.add_argument("--alerts", action="store_true",
                   help="enable the alerting plane (telemetry/alerts.py, "
                        "default fleet rule pack): GET /alerts, healthz "
                        "alerts block, exemplar capture; evaluation rides "
                        "the health loop, no extra scrape")
    p.add_argument("--alert-stale-after", type=float, default=10.0,
                   help="scrape_stale rule: seconds since a member's last "
                        "successful /metrics scrape before it alerts")
    p.add_argument("--alert-latency-drift", type=float, default=0.05,
                   help="latency_anomaly rule: smallest p99 drift (s) "
                        "worth a robust-z unit — a shift of ~12x this "
                        "over the rolling baseline pages")
    p.add_argument("--alert-webhook", default=None, metavar="URL",
                   help="POST every alert transition to this URL (bounded "
                        "timeout + retries, off the evaluation path)")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    from gan_deeplearning4j_tpu.deploy import CanaryThresholds
    from gan_deeplearning4j_tpu.fleet.autoscaler import AutoscalerConfig
    from gan_deeplearning4j_tpu.fleet.manager import FleetManager
    from gan_deeplearning4j_tpu.fleet.router import (
        FleetRouter,
        make_router_server,
    )
    from gan_deeplearning4j_tpu.telemetry.slo import SLOConfig
    from gan_deeplearning4j_tpu.telemetry.trace import TRACER, configure_from_env

    if args.telemetry:
        TRACER.enable()
    else:
        configure_from_env()
    worker_args = []
    if args.buckets:
        worker_args += ["--buckets", args.buckets]
    if args.replicas is not None:
        worker_args += ["--replicas", str(args.replicas)]
    if args.max_latency is not None:
        worker_args += ["--max-latency", str(args.max_latency)]
    if args.timeout is not None:
        worker_args += ["--timeout", str(args.timeout)]
    ports = None
    if args.worker_ports:
        ports = [int(x) for x in args.worker_ports.split(",") if x.strip()]
        if len(ports) != args.workers:
            p.error(f"--worker-ports names {len(ports)} ports for "
                    f"--workers {args.workers}")
    router = FleetRouter(
        request_timeout=args.request_timeout,
        probe_interval=args.probe_interval,
        retry_ratio=args.retry_ratio,
        retry_burst=args.retry_burst,
        breaker_kwargs={
            "consecutive_failures": args.eject_failures,
            "reopen_after": args.reopen_after,
        },
        slo_config=SLOConfig(
            availability_target=args.slo_availability,
            latency_threshold_s=args.slo_latency_ms / 1e3,
            latency_target=args.slo_latency_target,
            fast_window_s=args.slo_fast_window,
            slow_window_s=args.slo_slow_window,
        ),
    )
    autoscale = None
    if args.autoscale:
        autoscale = AutoscalerConfig(
            min_workers=args.workers,
            max_workers=args.max_workers or 2 * args.workers,
            up_pressure=args.scale_up_pressure,
            down_pressure=args.scale_down_pressure,
            up_consecutive=args.scale_up_ticks,
            down_consecutive=args.scale_down_ticks,
            interval_s=args.scale_interval,
            up_cooldown_s=args.scale_up_cooldown,
            down_cooldown_s=args.scale_down_cooldown,
            brownout_exit_ticks=args.brownout_exit_ticks,
            brownout_max_rows=args.brownout_max_rows,
            brownout_deadline_s=args.brownout_deadline_ms / 1e3,
        )
    manager = FleetManager(
        router, args.store,
        num_workers=args.workers, ports=ports, host=args.host,
        worker_args=worker_args, log_dir=args.log_dir,
        poll_interval=args.poll,
        drain_timeout=args.drain_timeout,
        warm_timeout=args.warm_timeout,
        hang_restart_after=args.hang_restart,
        canary_data=args.canary_data,
        canary_samples=args.canary_samples,
        canary_seed=args.canary_seed,
        canary_feature=args.canary_feature,
        thresholds=CanaryThresholds(
            fid_ratio_max=args.canary_fid_ratio,
            fid_slack=args.canary_fid_slack,
            accuracy_drop_max=args.canary_acc_drop,
        ),
        telemetry=args.telemetry,
        autoscale=autoscale,
        spawn_backoff_base=args.spawn_backoff,
        spawn_backoff_max=args.spawn_backoff_max,
        compilation_cache=args.compilation_cache,
    )
    if args.alerts:
        from gan_deeplearning4j_tpu.telemetry.alerts import (
            AlertManager,
            WebhookSink,
            default_fleet_rules,
            log_sink,
        )

        sinks = [log_sink]
        if args.alert_webhook:
            sinks.append(WebhookSink(args.alert_webhook))
        router.attach_alerts(AlertManager(
            default_fleet_rules(
                probe_interval_s=args.probe_interval,
                scrape_stale_after_s=args.alert_stale_after,
                latency_drift_floor_s=args.alert_latency_drift,
                annotate_member=router.annotate_member),
            sinks=tuple(sinks)))
    log = logging.getLogger(__name__)
    # bind the router port BEFORE spawning workers: a bind failure must
    # not leave N orphaned worker subprocesses behind
    server = make_router_server(router, args.host, args.port)
    try:
        manager.start(boot_wait=args.boot_wait)
        log.info("fleet router on http://%s:%d (%d workers, generation %s)",
                 args.host, server.server_address[1], len(manager.slots),
                 manager.generation)
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        manager.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
