"""Per-worker health: active probing + passive outlier ejection.

A fleet router cannot trust a worker list — workers get SIGKILLed, hang
under SIGSTOP, restart into a warmup window, or rot behind a full queue.
This module is the router's opinion of one worker, built from two signal
streams:

- **active** — a bounded ``GET /healthz`` probe (:func:`probe_worker`).
  Only ``"ok"`` admits: ``"warming"`` means the compile ladder is still
  building (routing there buys tail latency), ``"draining"`` means the
  manager is rotating the worker out, ``"error"`` means a failed warmup
  that would pay serve-time compiles per request.
- **passive** — the outcome of every proxied request
  (:meth:`CircuitBreaker.record`). Consecutive failures OR a windowed
  error rate trips the breaker, so both a hard-down worker (every attempt
  fails) and a flaky one (interleaved successes keep any consecutive
  counter low) get ejected.

The breaker is the classic three-state machine, with admission gates the
serving tier needs:

``init`` → (first successful probe) → ``closed`` (healthy, routable)
→ (trip) → ``open`` (ejected, unroutable, backoff doubles per re-trip)
→ (reopen deadline) → ``half_open`` (ONE active probe may be spent)
→ probe ok → ``closed`` / probe fails → ``open`` again.

Everything takes an injectable ``clock`` so tests drive the state machine
without wall-clock sleeps. Thread-safety: one lock per breaker — the
router's request threads record outcomes concurrently with the health
loop's probes.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from collections import deque
from typing import Optional, Tuple

#: /healthz statuses that admit a worker into the routable pool
ADMITTABLE = ("ok",)

#: breaker states (gauge order: the fleet_worker_state metric exports the
#: index)
STATES = ("init", "closed", "open", "half_open")


def http_json(url: str, timeout: float, method: str = "GET",
              data: Optional[bytes] = None) -> Optional[dict]:
    """One bounded HTTP round trip decoded as JSON; None on ANY failure
    (refused, reset, timed out, non-JSON body). The single network helper
    behind probes, scrapes, and the manager's admin posts — failure is a
    health signal on every one of those paths, never an exception."""
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except (urllib.error.URLError, OSError, ValueError):
        return None


def probe_worker(base_url: str, timeout: float = 2.0
                 ) -> Tuple[bool, Optional[dict]]:
    """One bounded active probe: ``(admittable, healthz body or None)``.
    Never raises — a dead socket is exactly the signal being probed for."""
    body = http_json(f"{base_url}/healthz", timeout=timeout)
    if body is None:
        return False, None
    return body.get("status") in ADMITTABLE, body


class CircuitBreaker:
    """Trip/eject/re-admit state for one worker.

    ``consecutive_failures`` trips after N back-to-back failures;
    ``error_rate``/``rate_window``/``rate_min_samples`` trip when the
    failure fraction over the last ``rate_window`` outcomes exceeds
    ``error_rate`` with at least ``rate_min_samples`` observed (the flaky-
    worker path a consecutive counter misses). ``reopen_after`` is the
    initial open→half-open backoff; every re-trip from half-open doubles
    it up to ``reopen_max``.
    """

    def __init__(self, *, consecutive_failures: int = 3,
                 error_rate: float = 0.5, rate_window: int = 20,
                 rate_min_samples: int = 10, reopen_after: float = 1.0,
                 reopen_max: float = 30.0, clock=None):
        if consecutive_failures < 1:
            raise ValueError("consecutive_failures must be >= 1")
        if not 0.0 < error_rate <= 1.0:
            raise ValueError("error_rate must be in (0, 1]")
        import time

        self._clock = clock or time.monotonic
        self.consecutive_failures = consecutive_failures
        self.error_rate = error_rate
        self.rate_min_samples = rate_min_samples
        self.reopen_after = reopen_after
        self.reopen_max = reopen_max
        self._lock = threading.Lock()
        self._state = "init"
        self._fail_streak = 0
        self._window: deque = deque(maxlen=rate_window)
        self._backoff = reopen_after
        self._reopen_at: Optional[float] = None
        self.trips = 0  # lifetime ejections (router metrics)

    # -- state ----------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state()

    def _effective_state(self) -> str:
        # under the lock: an expired open deadline IS half-open — the
        # transition is lazy so no timer thread is needed
        if (self._state == "open" and self._reopen_at is not None
                and self._clock() >= self._reopen_at):
            self._state = "half_open"
        return self._state

    @property
    def routable(self) -> bool:
        """True when requests may be sent to this worker. Half-open is NOT
        routable — re-admission is spent on one active probe, not on a
        user's request."""
        return self.state == "closed"

    def probe_due(self) -> bool:
        """True when the health loop should spend an active probe here:
        half-open (the single re-admission probe) or still in init
        (a freshly registered or relaunched worker warming up)."""
        return self.state in ("init", "half_open")

    # -- signal intake ---------------------------------------------------
    def record(self, ok: bool) -> Optional[str]:
        """Passive outcome of one proxied request. Returns ``"tripped"``
        when THIS record ejected the worker (the caller counts
        ejections), else None."""
        with self._lock:
            if self._state not in ("closed",):
                return None  # outcomes while ejected don't re-trip
            self._window.append(ok)
            self._fail_streak = 0 if ok else self._fail_streak + 1
            if ok:
                return None
            failures = sum(1 for o in self._window if not o)
            rate_tripped = (len(self._window) >= self.rate_min_samples
                            and failures / len(self._window)
                            > self.error_rate)
            if self._fail_streak >= self.consecutive_failures or rate_tripped:
                self._trip()
                return "tripped"
            return None

    def probe_result(self, ok: bool) -> Optional[str]:
        """Outcome of one active probe. In half-open/init a success closes
        the breaker (worker admitted — returns ``"admitted"``); a
        half-open failure re-opens with doubled backoff. Init failures
        just stay init: a warming worker is not *failing*, it is not
        ready yet."""
        with self._lock:
            state = self._effective_state()
            if ok:
                if state in ("init", "half_open", "open"):
                    self._state = "closed"
                    self._fail_streak = 0
                    self._window.clear()
                    self._backoff = self.reopen_after
                    self._reopen_at = None
                    return "admitted"
                return None
            if state == "half_open":
                # the single re-admission probe failed: back to open,
                # doubled backoff (a hard-down worker costs one probe per
                # widening interval, not a probe storm)
                self._state = "open"
                self._backoff = min(self.reopen_max, self._backoff * 2)
                self._reopen_at = self._clock() + self._backoff
            return None

    def eject(self) -> None:
        """Force the breaker open (manager-side: the worker process is
        known dead or is being force-restarted)."""
        with self._lock:
            if self._state != "open":
                self._trip()

    def reset(self) -> None:
        """Back to init (a relaunched process behind the same worker id:
        it must re-earn admission through a probe)."""
        with self._lock:
            self._state = "init"
            self._fail_streak = 0
            self._window.clear()
            self._backoff = self.reopen_after
            self._reopen_at = None

    def _trip(self) -> None:
        # under the lock
        self._state = "open"
        self.trips += 1
        self._reopen_at = self._clock() + self._backoff

    def snapshot(self) -> dict:
        with self._lock:
            state = self._effective_state()
            return {
                "state": state,
                "fail_streak": self._fail_streak,
                "trips": self.trips,
                "reopen_in_s": (
                    None if self._reopen_at is None or state != "open"
                    else max(0.0, self._reopen_at - self._clock())),
            }
