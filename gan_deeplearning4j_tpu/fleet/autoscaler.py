"""Autoscaler — SLO-driven elastic fleet sizing with graceful brownout.

The fleet served exactly the worker count it was launched with: a burst
could only be answered with honest 503s and an idle fleet burned N
workers forever. This module closes the loop the ROADMAP queued — the
admission signal (``telemetry/slo.py`` multi-window burn rates, fail
closed on empty windows) and the safe scale-down path (the manager's
draining restarts) already existed; the autoscaler is the controller
that connects them to fleet size:

- **signals** — one bounded read per tick of the same data
  ``GET /metrics?scope=fleet`` serves (the router's merged registry
  snapshot refreshes the SLO gauges) plus the router's live per-worker
  scrapes: routable count, total queue depth + in-flight (the occupancy
  pressure), and the availability/latency burn rates. A scrape that
  fails, or a signal that is missing or NaN, yields NO decision — the
  size HOLDS. An autoscaler that cannot see the fleet must not resize
  it; "no data" and "idle" are different claims (the same fail-closed
  stance the SLO tracker takes on empty windows).
- **hysteresis + cooldowns** — pressure must exceed the up threshold
  (or the SLO must be burning on BOTH windows of an objective) for
  ``up_consecutive`` ticks before a scale-up, and sit under the down
  threshold with no burn for ``down_consecutive`` ticks before a
  scale-down; each action arms its own cooldown. One noisy tick never
  moves the fleet, and the fleet never flaps between sizes.
- **scale-up** — a new worker slot spawns from the fleet's CURRENT
  bundle (the checkpoint store generation every other worker serves)
  and must re-earn router admission through the normal init-probe path
  before it counts as capacity — the pressure math only ever divides by
  *routable* workers, so a booting worker cannot flatter the signal.
  A spawn that wedges is bounded by the manager's boot timeout, and a
  spawn that dies before ever becoming routable relaunches under the
  manager's capped exponential backoff — never a hot relaunch loop.
- **scale-down** — only through the drain path: the LEAST-LOADED
  routable worker above ``min_workers`` is unrouted, drained (bounded),
  SIGTERMed, and removed. No in-flight request is ever dropped by a
  resize.
- **brownout** — at ``max_workers`` under continuing overload there is
  no capacity left to add, so degradation must be *ordered*, not
  emergent: the router enters tiered admission control. Tier 1 sheds
  oversized ``sample`` slabs (the largest single cost a request can
  impose) with an honest 503; tier 2 additionally shrinks effective
  deadlines so queued work is shed early instead of timing out late.
  The state is observable — ``"brownout"`` in the router's ``/healthz``
  and the ``fleet_brownout`` gauge — and exits (tier by tier) once
  pressure stays under the up threshold for ``brownout_exit_ticks``.

Resizes serialize with rolling upgrades through the manager's cycle
lock: a resize decided mid-roll *queues* (the streaks persist and the
action fires on the first post-roll tick) rather than interleaving with
the rotation. Crash supervision keeps running during a resize — the
supervise loop owns relaunches, the autoscaler only adds/removes slots.

``scripts/fleet_drill.py --autoscale`` proves the whole story under a
~10x closed-loop burst (docs/FLEET.md "Autoscaling").
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time
from typing import Callable, Optional

from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import TRACER

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class AutoscalerConfig:
    """Targets, thresholds, and pacing. Pressure is
    ``(queue_depth + in_flight) / routable`` — demand per unit of live
    capacity; with N closed-loop clients it reads ~N/routable."""

    min_workers: int = 1
    max_workers: int = 4
    #: pressure at/above which a tick counts toward scale-up
    up_pressure: float = 3.0
    #: pressure at/below which a tick counts toward scale-down
    down_pressure: float = 1.0
    #: SLO burn rate (both windows of one objective) that counts a tick
    #: toward scale-up even when queues look shallow — NaN never counts
    up_burn: float = 1.0
    #: consecutive qualifying ticks before acting (hysteresis)
    up_consecutive: int = 3
    down_consecutive: int = 10
    #: seconds between decision ticks
    interval_s: float = 1.0
    #: per-direction cooldowns armed after each resize
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 15.0
    #: brownout: enter after up_consecutive overloaded ticks AT max size;
    #: escalate tier 1 -> 2 after the same count again; de-escalate after
    #: brownout_exit_ticks calm ticks
    brownout_exit_ticks: int = 5
    #: tier-1 admission bound: /v1/sample slabs with more rows shed
    brownout_max_rows: int = 32
    #: tier-2 effective-deadline cap injected into admitted requests
    brownout_deadline_s: float = 1.0

    def validate(self) -> "AutoscalerConfig":
        if not 1 <= self.min_workers <= self.max_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{self.min_workers}..{self.max_workers}")
        if not 0.0 <= self.down_pressure < self.up_pressure:
            raise ValueError(
                "need 0 <= down_pressure < up_pressure (the hysteresis "
                f"band), got {self.down_pressure}/{self.up_pressure}")
        if self.up_consecutive < 1 or self.down_consecutive < 1:
            raise ValueError("consecutive tick counts must be >= 1")
        if self.brownout_exit_ticks < 1:
            # 0 would read `calm_streak >= 0` — always true — and flap
            # the brownout enter/exit every cycle under steady overload
            raise ValueError("brownout_exit_ticks must be >= 1")
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if self.up_cooldown_s < 0 or self.down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")
        if self.brownout_max_rows < 1:
            raise ValueError("brownout_max_rows must be >= 1")
        if self.brownout_deadline_s <= 0:
            raise ValueError("brownout_deadline_s must be > 0")
        return self


class Autoscaler:
    """The control loop. ``tick()`` is driven by the manager's supervise
    loop (no thread of its own); ``clock`` and ``scrape`` are injectable
    so the state machine is testable without sockets or sleeps.

    ``scrape`` returns the signal dict or ``None`` (unreachable); the
    default reads the router in-process — the same merged snapshot
    ``GET /metrics?scope=fleet`` serves, plus the live worker scrapes.
    """

    def __init__(self, manager, config: AutoscalerConfig, *,
                 clock: Callable[[], float] = time.monotonic,
                 scrape: Optional[Callable[[], Optional[dict]]] = None):
        self.manager = manager
        self.config = config.validate()
        self._clock = clock
        self._scrape = scrape or self._default_scrape
        self._lock = threading.Lock()
        self._next_tick = 0.0
        self._cooldown_until = 0.0
        self._up_streak = 0
        self._down_streak = 0
        self._calm_streak = 0
        self._last_decision = "idle"
        self._last_signals: Optional[dict] = None
        self._scale_ups = 0
        self._scale_downs = 0
        self._deferred = 0
        registry = get_registry()
        self._c_decisions = registry.counter(
            "fleet_autoscale_decisions_total",
            "autoscaler decisions by action (hold actions included: an "
            "autoscaler that cannot see the fleet holds, observably)",
            labelnames=("action",))
        self._g_target = registry.gauge(
            "fleet_workers_target",
            "worker count the autoscaler is converging the fleet toward")
        self._g_target.set(len(manager.slots) if manager.slots
                           else config.min_workers)

    # -- signals ---------------------------------------------------------
    def _default_scrape(self) -> Optional[dict]:
        """One in-process read of the signals ``GET /metrics?scope=fleet``
        serves — the SLO burn rates and the per-worker queue/in-flight
        state the health loop already scrapes — WITHOUT the per-worker
        HTTP fan-out that endpoint performs (a tick must never block the
        supervise thread behind an unreachable worker's probe timeout).
        Any failure is None — the caller holds; an out-of-process
        deployment injects ``scrape`` and gets the same fail-closed
        contract on a dead router."""
        router = self.manager.router
        try:
            # router.member_signals() is THE shared signal seam: one
            # pass over the health loop's scraped worker state, also
            # feeding the fleet_member_* gauges the alert plane and the
            # prom surface read — autoscaling and alerting pay for the
            # same scrape exactly once
            signals = router.member_signals()
            return {
                "routable": signals["routable"],
                "queue_depth": signals["queue_depth"],
                "in_flight": signals["in_flight"],
                "burn_rates": router.slo.burn_rates(),
            }
        except Exception:
            logger.exception("autoscaler scrape failed")
            return None

    @staticmethod
    def _burning(burn_rates: dict, threshold: float) -> bool:
        """True when any objective burns on BOTH its windows. NaN is not
        burning — an empty window must not trigger a resize (it triggers
        a HOLD through the missing-signal path when the whole scrape is
        gone; here it just fails to qualify the tick)."""
        for windows in (burn_rates or {}).values():
            values = list(windows.values())
            if values and all(
                    not math.isnan(b) and b >= threshold for b in values):
                return True
        return False

    # -- the decision state machine --------------------------------------
    def decide(self, signals: Optional[dict]) -> str:
        """Fold one tick's signals into the streaks and return the
        action: ``up`` / ``down`` / ``brownout_enter`` /
        ``brownout_escalate`` / ``brownout_exit`` / ``hold`` /
        ``hold_no_signals`` / ``hold_cooldown``. Pure state (no process
        side effects) — :meth:`tick` applies the action."""
        cfg = self.config
        now = self._clock()
        if signals is None:
            # fail closed: never act on absent data, and reset the
            # streaks — evidence gathered before the blackout is stale
            self._up_streak = self._down_streak = self._calm_streak = 0
            return "hold_no_signals"
        routable = signals.get("routable")
        queue = signals.get("queue_depth")
        inflight = signals.get("in_flight")
        if any(v is None or (isinstance(v, float) and math.isnan(v))
               for v in (routable, queue, inflight)):
            self._up_streak = self._down_streak = self._calm_streak = 0
            return "hold_no_signals"
        if routable < 1:
            # nothing admitted: a resize decision divides by live
            # capacity it cannot see. Supervision (relaunch, backoff)
            # owns a fully-down fleet, not the autoscaler.
            self._up_streak = self._down_streak = self._calm_streak = 0
            return "hold_no_signals"
        pressure = (queue + inflight) / routable
        brownout = self.manager.router.brownout_level
        # while browned out, the burn signal is contaminated by our OWN
        # admission control: every tier-1 shed is an honest 503 the SLO
        # rightly counts as a failure — reading it as "still overloaded"
        # would latch the brownout forever on a trickle of large slabs
        # (and pin the fleet at max). Under brownout, pressure alone is
        # the controller's evidence; the burn re-arms once we exit.
        burning = (brownout == 0
                   and self._burning(signals.get("burn_rates"), cfg.up_burn))
        overloaded = pressure >= cfg.up_pressure or burning
        calm = pressure <= cfg.down_pressure and not burning
        self._up_streak = self._up_streak + 1 if overloaded else 0
        self._down_streak = self._down_streak + 1 if calm else 0
        self._calm_streak = 0 if overloaded else self._calm_streak + 1

        size = len(self.manager.slots)
        # brownout transitions ignore the resize cooldowns: admission
        # control is the pressure valve precisely when resizing is
        # exhausted, and releasing it promptly is as ordered as entering
        if brownout > 0 and self._calm_streak >= cfg.brownout_exit_ticks:
            self._calm_streak = 0
            return "brownout_exit"
        if overloaded and self._up_streak >= cfg.up_consecutive:
            if size >= cfg.max_workers:
                if brownout == 0:
                    self._up_streak = 0
                    return "brownout_enter"
                if brownout == 1:
                    self._up_streak = 0
                    return "brownout_escalate"
                return "hold"  # already at the deepest tier
            if now < self._cooldown_until:
                return "hold_cooldown"
            self._up_streak = 0
            return "up"
        if (calm and self._down_streak >= cfg.down_consecutive
                and brownout == 0):
            if size <= cfg.min_workers:
                return "hold"
            if now < self._cooldown_until:
                return "hold_cooldown"
            self._down_streak = 0
            return "down"
        return "hold"

    # -- driving ---------------------------------------------------------
    def tick(self) -> Optional[str]:
        """One throttled control-loop pass: scrape, decide, act. Returns
        the action taken (None between intervals). Resize actions
        serialize with rolling upgrades through the manager's cycle
        lock — a roll in flight defers the resize to the next tick, it
        never interleaves with the rotation."""
        now = self._clock()
        with self._lock:
            if now < self._next_tick:
                return None
            self._next_tick = now + self.config.interval_s
            signals = self._scrape()
            action = self.decide(signals)
            self._last_signals = signals
        applied = self._apply(action)
        with self._lock:
            self._last_decision = applied
        self._c_decisions.labels(action=applied).inc()
        if TRACER.enabled and applied not in ("hold", "hold_cooldown"):
            pressure = None
            try:
                pressure = round(
                    (signals["queue_depth"] + signals["in_flight"])
                    / max(1, signals["routable"]), 3)
            except (KeyError, TypeError):
                pass  # partial signals: decide() already held on them
            TRACER.instant("fleet.autoscale", {
                "action": applied,
                "size": len(self.manager.slots),
                "pressure": pressure,
            })
        return applied

    def _apply(self, action: str) -> str:
        cfg = self.config
        mgr = self.manager
        if action == "up":
            if not mgr._cycle_lock.acquire(blocking=False):
                self._note_deferred("up")
                return "deferred_roll"  # a roll owns the fleet right now
            try:
                with TRACER.span("fleet.scale_up"):
                    slot = mgr.scale_up_one()
            finally:
                mgr._cycle_lock.release()
            if slot is None:
                return "hold"
            with self._lock:
                self._scale_ups += 1
                self._cooldown_until = self._clock() + cfg.up_cooldown_s
            self._g_target.set(len(mgr.slots))
            logger.info("autoscaler scaled up: %d workers (spawned %s)",
                        len(mgr.slots), slot.id)
            return "up"
        if action == "down":
            if not mgr._cycle_lock.acquire(blocking=False):
                self._note_deferred("down")
                return "deferred_roll"
            try:
                with TRACER.span("fleet.scale_down"):
                    removed = mgr.scale_down_one()
            finally:
                mgr._cycle_lock.release()
            if not removed:
                return "hold"
            with self._lock:
                self._scale_downs += 1
                self._cooldown_until = self._clock() + cfg.down_cooldown_s
            self._g_target.set(len(mgr.slots))
            logger.info("autoscaler scaled down: %d workers", len(mgr.slots))
            return "down"
        if action == "brownout_enter":
            mgr.router.set_brownout(1, max_rows=cfg.brownout_max_rows,
                                    deadline_s=cfg.brownout_deadline_s)
            logger.warning("brownout tier 1: at max size (%d) under "
                           "sustained overload — shedding sample slabs "
                           "over %d rows", len(mgr.slots),
                           cfg.brownout_max_rows)
            return action
        if action == "brownout_escalate":
            mgr.router.set_brownout(2, max_rows=cfg.brownout_max_rows,
                                    deadline_s=cfg.brownout_deadline_s)
            logger.warning("brownout tier 2: overload continues — "
                           "capping effective deadlines at %.2fs",
                           cfg.brownout_deadline_s)
            return action
        if action == "brownout_exit":
            level = mgr.router.brownout_level
            mgr.router.set_brownout(max(0, level - 1))
            logger.info("brownout de-escalated to tier %d",
                        mgr.router.brownout_level)
            return action
        return action

    def _note_deferred(self, direction: str) -> None:
        """A resize deferred behind a roll keeps its evidence: re-arm the
        streak decide() consumed, so the action fires on the first
        post-roll tick instead of re-earning the whole hysteresis
        window while the overload (or idle burn) continues."""
        with self._lock:
            self._deferred += 1
            if direction == "up":
                self._up_streak = self.config.up_consecutive
            else:
                self._down_streak = self.config.down_consecutive

    # -- observability ---------------------------------------------------
    def status(self) -> dict:
        cfg = self.config
        with self._lock:
            signals = self._last_signals
            return {
                "min_workers": cfg.min_workers,
                "max_workers": cfg.max_workers,
                "last_decision": self._last_decision,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "deferred": self._deferred,
                "cooldown_remaining_s": round(
                    max(0.0, self._cooldown_until - self._clock()), 3),
                "signals": signals,
                "brownout_level": self.manager.router.brownout_level,
                # the reaction-time surface (fleet_scaleup_routable_
                # seconds): how long the most recent worker admissions
                # took from launch to routable — what a scale-up
                # actually buys and when (warm elasticity shrinks this)
                "scaleup_routable_s": [
                    round(s.routable_s, 3) for s in self.manager.slots
                    if s.routable_s is not None
                ],
            }
