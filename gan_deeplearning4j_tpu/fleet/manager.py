"""FleetManager — worker process lifecycle and rolling generation upgrades.

The manager owns what the router must not: processes and model versions.

- **spawn/supervise** — each worker slot runs ``python -m
  gan_deeplearning4j_tpu.serving --bundle <generation dir>`` on its own
  port. A dead process (SIGKILL, OOM, crash) is relaunched on the same
  port with the fleet's current bundle; a worker whose breaker stays open
  while its process is alive (a SIGSTOP-style hang) is force-restarted
  after ``hang_restart_after`` — long enough that a transient stall gets
  its half-open re-admission chance first. A live worker that never
  reaches its FIRST admission (hung mid-warmup, where init probe failures
  cannot trip the breaker) is force-restarted after ``warm_timeout``; a
  worker that DIES before its first admission relaunches on a capped
  exponential backoff (``spawn_backoff_base``/``spawn_backoff_max``,
  ``fleet_spawn_failures_total``) — a bundle that kills every boot must
  not turn supervision into a fork loop (jaxlint JG021).
- **elastic resize** — with an :class:`~.autoscaler.AutoscalerConfig`
  the supervise loop ticks the SLO-driven control loop
  (fleet/autoscaler.py): :meth:`FleetManager.scale_up_one` spawns a new
  slot from the current bundle (it re-earns admission before counting
  as capacity), :meth:`FleetManager.scale_down_one` retires the
  least-loaded routable worker through the drain path. Resizes take the
  same cycle lock as rolling upgrades — they queue behind a roll,
  never interleave with it.
- **draining restart** — the zero-lost worker rotation (docs/FLEET.md):
  mark draining at the router (no new requests), ``POST /admin/drain`` on
  the worker (its ``/healthz`` leaves the admittable set), watch its
  ``/metrics`` until queue and pipeline are empty (bounded by
  ``drain_timeout`` — a stuck in-flight forces the restart anyway),
  SIGTERM → relaunch → re-admit only after the health loop sees a warm
  ``"ok"``.
- **rolling upgrades, one canary decision per fleet** — a
  :class:`~deploy.watcher.StoreWatcher` polls the checkpoint store for a
  newer digest-valid serving generation. Admission is decided ONCE,
  before any worker is touched: quality probes run in a sidecar
  subprocess (``python -m gan_deeplearning4j_tpu.deploy probe``) against
  the candidate and incumbent bundles, compared under the same
  :class:`~deploy.canary.CanaryThresholds` the in-process gate uses
  (``compare_probes``). A pass rolls workers one at a time through
  draining restarts; a fail quarantines the generation through the store
  — fleet-wide, permanently, without restarting anything. A probe that
  *dies* (timeout, prober crash) is infrastructure failure, not a
  verdict: the decision is deferred to the next poll, and only
  ``probe_retries`` consecutive candidate-probe failures quarantine. If a
  rolled worker fails to come back healthy the roll HALTS: that worker is
  rolled back to the incumbent bundle and the candidate is quarantined (a
  generation that kills workers is worse than a canary miss).

Incumbent probes are cached per generation, so steady-state upgrades cost
one sidecar probe each.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from gan_deeplearning4j_tpu.deploy.canary import CanaryThresholds, compare_probes
from gan_deeplearning4j_tpu.deploy.watcher import BundleCandidate, StoreWatcher
from gan_deeplearning4j_tpu.fleet.health import http_json
from gan_deeplearning4j_tpu.fleet.router import FleetRouter, scrape_metrics
from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.trace import TRACER

logger = logging.getLogger(__name__)

SERVING_CLI = [sys.executable, "-m", "gan_deeplearning4j_tpu.serving"]
PROBE_CLI = [sys.executable, "-m", "gan_deeplearning4j_tpu.deploy"]


class WorkerProcess:
    """One spawned serving worker subprocess (stdout+stderr to a log
    file, so a crash is diagnosable after the fact)."""

    def __init__(self, cmd: List[str], log_path: str,
                 env: Optional[dict] = None, cwd: Optional[str] = None):
        self.cmd = list(cmd)
        self.log_path = log_path
        self._log = open(log_path, "a")
        self.proc = subprocess.Popen(cmd, stdout=self._log,
                                     stderr=self._log, env=env, cwd=cwd)

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self, grace: float = 10.0) -> None:
        """SIGTERM, bounded wait, then SIGKILL — a hung worker cannot
        stall a rotation forever."""
        if self.alive():
            self.proc.terminate()
            try:
                self.proc.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                try:
                    self.proc.wait(timeout=grace)
                except subprocess.TimeoutExpired:
                    pass
        try:
            self._log.close()
        except OSError:
            pass


class WorkerSlot:
    """One position in the fleet: a stable id + port whose process comes
    and goes across restarts."""

    def __init__(self, worker_id: str, port: int, host: str = "127.0.0.1"):
        self.id = worker_id
        self.host = host
        self.port = port
        self.base_url = f"http://{host}:{port}"
        self.process: Optional[WorkerProcess] = None
        self.bundle_path: Optional[str] = None
        self.restarts = 0
        self.open_since: Optional[float] = None  # breaker-open watermark
        self.launched_at: Optional[float] = None  # init-hang watermark
        # launch-to-first-admission seconds of the CURRENT process — the
        # elasticity number ("capacity means routable, not spawned"):
        # what a scale-up or restart actually costs before the router
        # sends this worker traffic. None until admission.
        self.routable_s: Optional[float] = None
        # spawn-failure backoff state: a process that dies before EVER
        # earning router admission relaunches on a capped exponential
        # schedule, not in a tight loop (docs/FLEET.md)
        self.ever_routable = False
        self.spawn_failures = 0
        self.next_launch_at: Optional[float] = None


class FleetManager:
    """Drives N :class:`WorkerSlot` behind a :class:`FleetRouter`.

    ``worker_args`` are extra CLI flags every worker gets (buckets,
    replicas, latency knobs). ``canary_data`` (an npz path) enables the
    fleet-level admission gate; without it a digest-valid newer
    generation rolls ungated. ``spawn`` is injectable for tests:
    ``(slot, bundle_path) -> WorkerProcess-like``.
    """

    def __init__(self, router: FleetRouter, store_root: str, *,
                 num_workers: int = 2, ports: Optional[List[int]] = None,
                 host: str = "127.0.0.1",
                 worker_args: Optional[List[str]] = None,
                 log_dir: str = ".",
                 poll_interval: float = 2.0,
                 drain_timeout: float = 30.0,
                 warm_timeout: float = 300.0,
                 hang_restart_after: float = 20.0,
                 canary_data: Optional[str] = None,
                 canary_samples: int = 256, canary_seed: int = 666,
                 canary_feature: str = "raw",
                 thresholds: Optional[CanaryThresholds] = None,
                 probe_timeout_s: float = 600.0, probe_retries: int = 3,
                 spawn=None, env: Optional[dict] = None,
                 telemetry: bool = False,
                 autoscale=None,
                 spawn_backoff_base: float = 0.5,
                 spawn_backoff_max: float = 30.0,
                 compilation_cache: Optional[str] = None):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if spawn_backoff_base <= 0 or spawn_backoff_max < spawn_backoff_base:
            raise ValueError("need 0 < spawn_backoff_base <= "
                             "spawn_backoff_max")
        from gan_deeplearning4j_tpu.resilience.store import CheckpointStore

        self.router = router
        self.store = CheckpointStore(store_root)
        self.watcher = StoreWatcher(store=self.store)
        self.host = host
        self.worker_args = list(worker_args or [])
        self.log_dir = log_dir
        self.poll_interval = poll_interval
        self.drain_timeout = drain_timeout
        self.warm_timeout = warm_timeout
        self.hang_restart_after = hang_restart_after
        self.canary_data = canary_data
        self.canary_samples = canary_samples
        self.canary_seed = canary_seed
        self.canary_feature = canary_feature
        self.thresholds = thresholds or CanaryThresholds()
        self.probe_timeout_s = probe_timeout_s
        self.probe_retries = probe_retries
        # span tracing on every WORKER process too (--telemetry on the
        # fleet CLI): without it the router's /debug/trace merge would
        # hold router spans only — trace propagation needs both ends
        self.telemetry = telemetry
        # shared persistent XLA cache dir for EVERY worker spawn (ISSUE
        # 19 warm elasticity): the first warmup pays the compiles, every
        # later spawn — scale_up_one, draining restarts, rolling
        # upgrades — reloads the AOT artifacts instead of recompiling,
        # which is what makes scale-up-to-routable fast
        self.compilation_cache = compilation_cache
        self._spawn = spawn or self._spawn_process
        self._env = env
        self.spawn_backoff_base = spawn_backoff_base
        self.spawn_backoff_max = spawn_backoff_max
        if ports is None:
            ports = [_free_port(host) for _ in range(num_workers)]
        self.slots = [WorkerSlot(f"w{i}", p, host)
                      for i, p in enumerate(ports)]
        # monotonic id allocator: a scaled-down slot's id is never reused
        # (its counters, logs, and events stay unambiguous)
        self._next_slot_idx = len(self.slots)
        self.generation: Optional[int] = None
        self.bundle_path: Optional[str] = None
        # dis-feature probes are pinned to ONE classifier for the fleet's
        # lifetime (the boot incumbent's): cached incumbent probes stay
        # comparable with every later candidate probe — re-pinning per
        # roll would compare FIDs measured in two different embedding
        # spaces
        self._feature_bundle: Optional[str] = None
        self._incumbent_probes: Dict[int, dict] = {}
        # candidate-probe failures by candidate token: an infrastructure
        # failure (timeout, prober OOM) defers the decision to the next
        # poll; only probe_retries consecutive failures on the SAME
        # candidate quarantine it (a bundle that reliably kills the
        # prober is evidence about the bundle)
        self._probe_failures: Dict[str, int] = {}
        self._state = "idle"  # idle|canary|rolling|halted
        # slot ids currently owned by roll machinery (rotation or halt
        # rollback): supervision must not touch them, but it keeps running
        # for every OTHER slot — a SIGKILL elsewhere in the fleet is
        # relaunched immediately, not after the roll finishes
        self._busy_slots: set = set()
        self._rolls = 0
        self._rejected = 0
        self._last_error: Optional[str] = None
        # bounded: a crash-looping worker appends one event per supervise
        # cycle — an unbounded list would leak for the manager's lifetime
        self.events: deque = deque(maxlen=256)
        self._lock = threading.Lock()
        self._cycle_lock = threading.Lock()
        # serializes _supervise_once across the loop thread and a roll's
        # in-wait supervision ticks — two threads must not both relaunch
        # the same dead worker
        self._supervise_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        registry = get_registry()
        self._c_rolls = registry.counter(
            "fleet_rolling_upgrades_total",
            "rolling generation upgrades completed fleet-wide")
        self._c_rejects = registry.counter(
            "fleet_canary_rejects_total",
            "candidate generations rejected by the fleet admission gate")
        self._c_restarts = registry.counter(
            "fleet_worker_restarts_total",
            "worker processes relaunched (crash, hang, or rotation)")
        self._g_generation = registry.gauge(
            "fleet_generation",
            "store generation the fleet is converged on (-1 = mid-roll)")
        self._c_spawn_failures = registry.counter(
            "fleet_spawn_failures_total",
            "worker processes that died before ever becoming routable "
            "(each schedules a backed-off relaunch, never a hot loop)")
        self._h_routable = registry.histogram(
            "fleet_scaleup_routable_seconds",
            "seconds from worker launch to first router admission — the "
            "autoscaler's real reaction time (capacity means routable, "
            "not spawned; docs/FLEET.md)")
        # the SLO-driven elastic control loop (fleet/autoscaler.py):
        # ticked by the supervise loop, resizes through scale_up_one /
        # scale_down_one under the same cycle lock rolling upgrades hold
        self.autoscaler = None
        if autoscale is not None:
            from gan_deeplearning4j_tpu.fleet.autoscaler import Autoscaler

            if not (autoscale.min_workers <= num_workers
                    <= autoscale.max_workers):
                raise ValueError(
                    f"num_workers={num_workers} outside the autoscaler's "
                    f"{autoscale.min_workers}..{autoscale.max_workers}")
            self.autoscaler = Autoscaler(self, autoscale)
        router.manager = self

    # -- lifecycle -------------------------------------------------------
    def start(self, boot_wait: float = 120.0) -> None:
        """Resolve the initial generation (waiting for a trainer's first
        publish, bounded), spawn every worker, start the router's health
        loop and the supervise thread."""
        deadline = time.monotonic() + boot_wait
        candidate = None
        while candidate is None:
            candidate = self.watcher.poll_once()
            if candidate is None:
                if time.monotonic() >= deadline:
                    raise RuntimeError(
                        f"no valid serving generation appeared in "
                        f"{self.store.root} within {boot_wait:.0f}s")
                time.sleep(0.25)
        # under the cycle lock even though the supervise thread starts
        # below: a concurrently-forced poll_now(wait=True) from another
        # thread must never observe generation set but bundle_path not
        with self._cycle_lock:
            self.generation = candidate.generation
            self.bundle_path = candidate.path
            self._feature_bundle = candidate.path
        self._g_generation.set(-1 if candidate.generation is None
                               else candidate.generation)
        for slot in self.slots:
            self._launch(slot, candidate.path)
        self.router.start_health_loop()
        self._stop.clear()
        t = threading.Thread(target=self._loop, name="fleet-manager",
                             daemon=True)
        self._thread = t
        t.start()

    def stop(self, timeout: float = 15.0) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self.router.stop()
        for slot in list(self.slots):
            if slot.process is not None:
                slot.process.stop()

    def status(self) -> dict:
        with self._lock:
            body = {
                "state": self._state,
                # deliberately not under _cycle_lock: that lock is held
                # for minutes during a roll and status() is the
                # observability endpoint that must stay responsive then;
                # a generation stale by one roll is an acceptable read
                "generation": self.generation,  # jaxlint: disable=JG024 (status must not block on the cycle lock)
                "rolls": self._rolls,
                "rejected": self._rejected,
                "last_error": self._last_error,
                "workers": [
                    {
                        "id": s.id, "port": s.port,
                        "pid": (s.process.pid if s.process is not None
                                else None),
                        "alive": (s.process is not None
                                  and s.process.alive()),
                        "restarts": s.restarts,
                        "spawn_failures": s.spawn_failures,
                        "routable_s": (None if s.routable_s is None
                                       else round(s.routable_s, 3)),
                        "bundle": s.bundle_path,
                    }
                    for s in self.slots
                ],
                "compilation_cache": self.compilation_cache,
            }
        if self.autoscaler is not None:
            body["autoscaler"] = self.autoscaler.status()
        return body

    def poll_now(self, wait: bool = True) -> dict:
        """Force a store poll (POST /admin/poll on the router). With
        ``wait`` the full cycle — canary and roll included — runs on the
        caller's thread; otherwise the supervise loop is woken."""
        if wait:
            with self._cycle_lock:
                try:
                    self._poll_cycle()
                except Exception as exc:
                    with self._lock:
                        self._last_error = f"{type(exc).__name__}: {exc}"
        else:
            self._wake.set()
        return self.status()

    # -- process control -------------------------------------------------
    def _worker_cmd(self, slot: WorkerSlot, bundle_path: str) -> List[str]:
        cmd = SERVING_CLI + [
            "--bundle", bundle_path,
            "--host", slot.host, "--port", str(slot.port),
            "--warmup", "eager",
        ]
        if self.telemetry:
            cmd.append("--telemetry")
        if self.compilation_cache:
            # THE warm-elasticity seam: without this flag every spawned
            # worker recompiled its full ladder from scratch (the bug
            # ISSUE 19 names) — the serving CLI has honored it since PR 4
            cmd += ["--compilation-cache", self.compilation_cache]
        return cmd + self.worker_args

    def _spawn_process(self, slot: WorkerSlot, bundle_path: str
                       ) -> WorkerProcess:
        log_path = os.path.join(self.log_dir, f"worker-{slot.id}.log")
        return WorkerProcess(self._worker_cmd(slot, bundle_path), log_path,
                             env=self._env)

    def _launch(self, slot: WorkerSlot, bundle_path: str) -> None:
        slot.process = self._spawn(slot, bundle_path)
        slot.bundle_path = bundle_path
        slot.open_since = None
        slot.launched_at = time.monotonic()
        slot.routable_s = None  # the NEW process re-earns its timing
        # the NEW process has not earned admission yet: if it dies before
        # it does, the relaunch goes through the spawn-failure backoff
        slot.ever_routable = False
        slot.next_launch_at = None
        try:
            ref = self.router.worker(slot.id)
        except KeyError:
            self.router.add_worker(slot.id, slot.base_url,
                                   pid=slot.process.pid)
        else:
            ref.pid = slot.process.pid
            ref.breaker.reset()  # a new process must re-earn admission
            # drop the dead process's /metrics snapshot: a stale
            # draining=True from the pre-restart worker must not keep the
            # fresh one out of the pool until the next scrape
            ref.update_scrape({})

    def _restart(self, slot: WorkerSlot, bundle_path: str,
                 reason: str) -> None:
        logger.warning("restarting worker %s (%s)", slot.id, reason)
        if slot.process is not None:
            slot.process.stop()
        slot.restarts += 1
        self._c_restarts.inc()
        self._launch(slot, bundle_path)
        with self._lock:
            self.events.append({"event": "restart", "worker": slot.id,
                                "reason": reason})

    def _wait_routable(self, slot: WorkerSlot, timeout: float) -> bool:
        """Wait for the router's health loop to admit the slot's worker
        (its /healthz must reach "ok" — warmup done)."""
        deadline = time.monotonic() + timeout
        ref = self.router.worker(slot.id)
        last_tick = time.monotonic()
        while time.monotonic() < deadline:
            if self._stop.is_set():
                return False  # shutting down — don't hold stop() hostage
            if ref.routable:
                return True
            if slot.process is not None and not slot.process.alive():
                return False  # died while warming — the caller decides
            last_tick = self._supervise_tick(last_tick)
            time.sleep(0.1)
        return False

    # -- draining restart -------------------------------------------------
    def drain_worker(self, slot: WorkerSlot) -> bool:
        """The drain half of a rotation: unroute, mark the worker
        draining, and wait (bounded) for its pipeline to empty. True when
        it fully drained; False means ``drain_timeout`` expired with work
        stuck in flight and the restart proceeds as a forced one."""
        self.router.mark_draining(slot.id, True)
        # best-effort: the worker may be dying anyway; failure means the
        # drain watch below sees an unscrapable worker and forces through
        http_json(f"{slot.base_url}/admin/drain", timeout=2.0,
                  method="POST", data=b"{}")
        deadline = time.monotonic() + self.drain_timeout
        last_tick = time.monotonic()
        while time.monotonic() < deadline:
            if self._stop.is_set():
                return False  # shutting down — don't hold stop() hostage
            m = scrape_metrics(slot.base_url, timeout=2.0)
            if m is None:
                return False  # unscrapable mid-drain: treat as stuck
            if (int(m.get("queue_depth", 0)) == 0
                    and int(m.get("pipeline", {}).get("in_flight", 0)) == 0):
                return True
            last_tick = self._supervise_tick(last_tick)
            time.sleep(0.05)
        return False

    def rotate_worker(self, slot: WorkerSlot, bundle_path: str) -> bool:
        """One draining restart onto ``bundle_path``. True when the
        relaunched worker came back healthy within ``warm_timeout``."""
        with self._lock:
            self._busy_slots.add(slot.id)
        try:
            with TRACER.span("fleet.rotate", worker=slot.id):
                drained = self.drain_worker(slot)
                self._restart(slot, bundle_path,
                              "rotation" if drained else "forced rotation "
                              "(drain timeout)")
                self.router.mark_draining(slot.id, False)
                return self._wait_routable(slot, self.warm_timeout)
        finally:
            with self._lock:
                self._busy_slots.discard(slot.id)

    # -- elastic resize (fleet/autoscaler.py drives these) ----------------
    def scale_up_one(self):
        """Add one worker slot spawned from the fleet's current bundle.
        The new worker re-earns router admission through the normal
        init-probe path before it ever counts as capacity; a boot that
        wedges is bounded by ``warm_timeout`` supervision and a boot
        that dies goes through the spawn-failure backoff. Returns the
        new slot, or None when there is no bundle to spawn from."""
        # one snapshot, not two reads: Autoscaler._apply holds _cycle_lock
        # (non-blocking acquire, a cross-class seam the static index cannot
        # see) for every resize, so the bundle cannot roll mid-call — and
        # the single read also kills the check-then-use window for any
        # future lockless caller
        bundle = self.bundle_path  # jaxlint: disable=JG024 (resize runs under _cycle_lock via Autoscaler._apply)
        if self._stop.is_set() or bundle is None:
            return None
        with self._lock:
            idx = self._next_slot_idx
            self._next_slot_idx += 1
        slot = WorkerSlot(f"w{idx}", _free_port(self.host), self.host)
        self._launch(slot, bundle)
        with self._lock:
            self.slots.append(slot)
            self.events.append({"event": "scale_up", "worker": slot.id,
                                "workers": len(self.slots)})
        logger.info("scale-up: spawned worker %s on port %d (%d slots)",
                    slot.id, slot.port, len(self.slots))
        return slot

    def scale_down_one(self) -> bool:
        """Retire the LEAST-LOADED routable worker through the drain
        path: unroute -> POST /admin/drain -> bounded drain watch ->
        SIGTERM -> remove from router and slot list. Never drops an
        in-flight request (a drain that times out forces through, the
        same bounded-beats-graceful trade a rotation makes). False when
        no routable worker exists to retire."""
        candidates = []
        for slot in list(self.slots):
            try:
                ref = self.router.worker(slot.id)
            except KeyError:
                continue
            if ref.routable:
                candidates.append((ref.load, slot))
        if not candidates:
            return False  # nothing safely retirable — hold instead
        _, slot = min(candidates, key=lambda pair: pair[0])
        with self._lock:
            self._busy_slots.add(slot.id)
        try:
            with TRACER.span("fleet.retire", worker=slot.id):
                drained = self.drain_worker(slot)
                if slot.process is not None:
                    slot.process.stop()
                self.router.remove_worker(slot.id)
                with self._lock:
                    if slot in self.slots:
                        self.slots.remove(slot)
                    self.events.append({"event": "scale_down",
                                        "worker": slot.id,
                                        "drained": drained,
                                        "workers": len(self.slots)})
        finally:
            with self._lock:
                self._busy_slots.discard(slot.id)
        logger.info("scale-down: retired worker %s (drained=%s, %d slots)",
                    slot.id, drained, len(self.slots))
        return True

    # -- the supervise loop ----------------------------------------------
    def _loop(self) -> None:
        next_poll = time.monotonic()
        while not self._stop.is_set():
            try:
                # supervision runs OUTSIDE _cycle_lock: a rolling upgrade
                # (minutes under the lock) must not block the relaunch of
                # a crashed worker elsewhere in the fleet. The slot being
                # rotated is skipped via _rotating instead.
                self._supervise_once()
                if self.autoscaler is not None:
                    # throttled internally; resize actions take
                    # _cycle_lock non-blocking so a roll in flight defers
                    # the resize instead of interleaving with it
                    self.autoscaler.tick()
                if time.monotonic() >= next_poll:
                    next_poll = time.monotonic() + self.poll_interval
                    with self._cycle_lock:
                        self._poll_cycle()
            except Exception as exc:  # supervision must outlive any bug
                logger.exception("fleet supervise cycle failed")
                with self._lock:
                    self._last_error = f"{type(exc).__name__}: {exc}"
            self._wake.wait(0.2)
            self._wake.clear()

    def _supervise_tick(self, last: float, every: float = 1.0) -> float:
        """Supervision from inside a rotation's bounded waits: the roll
        runs ON the supervise thread, so without these ticks a worker
        SIGKILLed elsewhere in the fleet would stay down for the whole
        rotation (minutes). Throttled; never lets a supervise bug break
        the rotation that hosts it."""
        now = time.monotonic()
        if now - last < every:
            return last
        try:
            self._supervise_once()
        except Exception:
            logger.exception("in-rotation supervise tick failed")
        return now

    def _supervise_once(self) -> None:
        if self._stop.is_set():
            return  # stop() owns the processes now
        with self._supervise_lock:
            self._supervise_locked()

    def _supervise_locked(self) -> None:
        now = time.monotonic()
        with self._lock:
            busy = set(self._busy_slots)
            slots = list(self.slots)  # the autoscaler resizes this list
        for slot in slots:
            if slot.id in busy:
                continue  # a rotation/rollback owns this slot's process
            if slot.process is not None and not slot.process.alive():
                rc = getattr(getattr(slot.process, "proc", None),
                             "returncode", None)
                if not slot.ever_routable:
                    # died before EVER earning admission: a bundle or
                    # environment that kills every boot would otherwise
                    # relaunch in a tight loop. Capped exponential
                    # backoff per consecutive failure; the counter makes
                    # the loop's absence observable.
                    if slot.next_launch_at is None:
                        slot.spawn_failures += 1
                        self._c_spawn_failures.inc()
                        delay = min(self.spawn_backoff_max,
                                    self.spawn_backoff_base
                                    * (2 ** (slot.spawn_failures - 1)))
                        slot.next_launch_at = now + delay
                        with self._lock:
                            self.events.append({
                                "event": "spawn_failure",
                                "worker": slot.id,
                                "failures": slot.spawn_failures,
                                "retry_in_s": round(delay, 3)})
                        logger.warning(
                            "worker %s died before becoming routable "
                            "(rc=%s, failure %d) — relaunch in %.2fs",
                            slot.id, rc, slot.spawn_failures, delay)
                        continue
                    if now < slot.next_launch_at:
                        continue  # still backing off
                # SIGKILL/crash: relaunch with the bundle this slot was
                # last launched on (mid-roll, an already-rotated slot must
                # come back on the candidate, not the fleet's pre-roll
                # bundle — a halted roll rolls it back by bundle_path)
                self._restart(slot, slot.bundle_path or self.bundle_path,
                              f"process died (rc={rc})")
                continue
            # hang detection: breaker open while the process is alive —
            # give the half-open path its chance first, then force it
            try:
                ref = self.router.worker(slot.id)
            except KeyError:
                continue
            state = ref.breaker.snapshot()["state"]
            if state in ("open", "half_open"):
                if slot.open_since is None:
                    slot.open_since = now
                elif now - slot.open_since >= self.hang_restart_after:
                    self._restart(slot,
                                  slot.bundle_path or self.bundle_path,
                                  "hung (breaker open past "
                                  f"{self.hang_restart_after:.0f}s)")
            elif state == "init":
                # a live process stuck BEFORE its first admission (SIGSTOP
                # or a wedged warmup): init probe failures never trip the
                # breaker — "not ready yet" is not "failing" — so the
                # open-watermark path above can never see this worker.
                # Bound it by warm_timeout, the same allowance a rotation
                # gets, then force the restart (which re-arms the clock).
                slot.open_since = None
                if (slot.launched_at is not None
                        and now - slot.launched_at >= self.warm_timeout):
                    self._restart(slot,
                                  slot.bundle_path or self.bundle_path,
                                  "never became healthy within "
                                  f"{self.warm_timeout:.0f}s of launch")
            else:
                slot.open_since = None
                if state == "closed":
                    if not slot.ever_routable:
                        # FIRST admission of this process: record
                        # launch-to-routable seconds — the number warm
                        # elasticity (shared compilation cache) shrinks
                        # and the autoscaler's reaction time is made of
                        if slot.launched_at is not None:
                            slot.routable_s = now - slot.launched_at
                            self._h_routable.observe(slot.routable_s)
                            with self._lock:
                                self.events.append({
                                    "event": "routable",
                                    "worker": slot.id,
                                    "seconds": round(slot.routable_s, 3)})
                    # admission earned: this process is no longer a spawn
                    # failure candidate, and the backoff ladder resets
                    slot.ever_routable = True
                    slot.spawn_failures = 0
                    slot.next_launch_at = None

    # -- rolling upgrades -------------------------------------------------
    def _poll_cycle(self) -> bool:
        """One watch→admit→roll pass. True when a candidate was handled."""
        candidate = self.watcher.poll_once(current_generation=self.generation)
        if candidate is None:
            return False
        return self._admit_and_roll(candidate)

    def _admit_and_roll(self, candidate: BundleCandidate) -> bool:
        gen = candidate.generation
        admitted_probe: Optional[dict] = None
        if self.canary_data is not None:
            if (self.canary_feature != "raw"
                    and self._feature_bundle is not None
                    and not os.path.isdir(self._feature_bundle)):
                # the pinned feature bundle was GC'd by store retention:
                # re-pin to the current incumbent and drop the cached
                # probe so both sides are re-measured in the new space.
                # If the incumbent is gone too, pin to the CANDIDATE —
                # the only embedding space still on disk; a missing pin
                # would fail every candidate probe and quarantine good
                # generations forever, when the documented behavior for
                # a GC'd incumbent is an ungated roll
                repin = self.bundle_path
                if repin is None or not os.path.isdir(repin):
                    repin = candidate.path
                self._feature_bundle = repin
                self._incumbent_probes = {}
            with self._lock:
                self._state = "canary"
            with TRACER.span("fleet.canary", generation=gen):
                try:
                    cand_probe = self._sidecar_probe(candidate.path)
                except Exception as exc:
                    return self._probe_failed(candidate, "candidate", exc)
                self._probe_failures.pop(candidate.token, None)
                try:
                    inc_probe = self._incumbent_probe()
                except Exception as exc:
                    return self._probe_failed(candidate, "incumbent", exc)
            if inc_probe is None:
                # the incumbent bundle was GC'd by store retention before
                # its probe was ever cached: no baseline exists, and none
                # ever will — roll ungated (logged) rather than wedging
                # every future upgrade behind a probe that cannot run
                with self._lock:
                    self.events.append({
                        "event": "ungated_roll", "generation": gen,
                        "reason": "incumbent bundle GC'd before its "
                                  "baseline probe was cached"})
                logger.warning(
                    "fleet candidate generation %s admitted UNGATED: "
                    "incumbent bundle is gone and no probe was cached", gen)
            else:
                decision = compare_probes(cand_probe, inc_probe,
                                          self.thresholds)
                if not decision.passed:
                    self._reject(candidate, f"canary: {decision.reason}",
                                 extra={"candidate_probe": decision.candidate,
                                        "incumbent_probe": decision.incumbent})
                    return True
            # remembered, but NOT cached as the baseline yet: the cache
            # rolls forward only after the roll completes — a halted roll
            # reverts to the incumbent, whose baseline must survive
            admitted_probe = cand_probe
        with self._lock:
            self._state = "rolling"
        self._g_generation.set(-1)
        old_generation, old_bundle = self.generation, self.bundle_path
        with TRACER.span("fleet.roll", generation=gen):
            for idx, slot in enumerate(self.slots):
                if self._stop.is_set() or not self.rotate_worker(
                        slot, candidate.path):
                    if self._stop.is_set():
                        # shutdown interrupted the roll (stop() kills the
                        # worker mid-rotation, making it LOOK unhealthy):
                        # that is infrastructure, not a verdict — do not
                        # quarantine the candidate, do not respawn workers
                        # the exiting process would orphan, and do not
                        # claim the fleet converged to gen
                        with self._lock:
                            self._state = "halted"
                            self.events.append({
                                "event": "roll_interrupted",
                                "generation": gen, "reason": "shutdown"})
                        return True
                    # HALT: a generation that cannot boot a healthy worker
                    # is quarantined fleet-wide, the failed slot is forced
                    # back to the incumbent, and every already-rotated
                    # slot rolls back too — no worker may keep serving a
                    # quarantined generation
                    self._reject(candidate,
                                 f"worker {slot.id} failed to come back "
                                 f"healthy on generation {gen} — roll "
                                 f"halted", state="halted")
                    with self._lock:
                        self._busy_slots.add(slot.id)
                    try:
                        self._restart(slot, old_bundle,
                                      "rollback to incumbent after halted "
                                      "roll")
                        self.router.mark_draining(slot.id, False)
                        self._wait_routable(slot, self.warm_timeout)
                    finally:
                        with self._lock:
                            self._busy_slots.discard(slot.id)
                    for done in self.slots[:idx]:
                        if done.bundle_path == candidate.path:
                            self.rotate_worker(done, old_bundle)
                    self._g_generation.set(
                        -1 if old_generation is None else old_generation)
                    return True
        self.generation = gen
        self.bundle_path = candidate.path
        if gen is not None and admitted_probe is not None:
            # the candidate IS the incumbent now: its probe is the next
            # comparison's baseline (one sidecar probe per roll)
            self._incumbent_probes = {gen: admitted_probe}
        self._g_generation.set(-1 if gen is None else gen)
        self._c_rolls.inc()
        with self._lock:
            self._rolls += 1
            self._state = "idle"
            self._last_error = None
            self.events.append({"event": "roll", "from": old_generation,
                                "to": gen})
        logger.info("fleet rolled: generation %s -> %s", old_generation, gen)
        return True

    def _probe_failed(self, candidate: BundleCandidate, which: str,
                      exc: Exception) -> bool:
        """A sidecar probe that DIED (timeout, OOM, prober crash) is an
        infrastructure signal, not a canary verdict — quarantining on it
        would permanently reject a possibly-good generation. Defer: the
        candidate is not discarded, so the next poll retries it. Only a
        candidate whose own probe fails ``probe_retries`` consecutive
        times is rejected; an incumbent-probe failure never is (it says
        nothing about the candidate)."""
        err = f"{which} probe failed: {type(exc).__name__}: {exc}"
        if which == "candidate":
            n = self._probe_failures.get(candidate.token, 0) + 1
            self._probe_failures[candidate.token] = n
            if n >= self.probe_retries:
                self._probe_failures.pop(candidate.token, None)
                self._reject(candidate,
                             f"{err} ({n} consecutive attempts)")
                return True
            err = f"{err} (attempt {n}/{self.probe_retries})"
        with self._lock:
            self._state = "idle"
            self._last_error = err
            self.events.append({"event": "probe_deferred",
                                "generation": candidate.generation,
                                "reason": err})
        logger.warning("fleet candidate generation %s deferred: %s",
                       candidate.generation, err)
        return True

    def _reject(self, candidate: BundleCandidate, reason: str,
                extra: Optional[dict] = None, state: str = "idle") -> None:
        # ONE fleet-wide decision: quarantine through the store so the
        # generation is invisible to every future reader — no worker ever
        # sees it
        self.watcher.discard(candidate, reason, quarantine=True)
        self._c_rejects.inc()
        with self._lock:
            self._rejected += 1
            self._state = state
            self._last_error = reason
            self.events.append({"event": "reject",
                                "generation": candidate.generation,
                                "reason": reason, **(extra or {})})
        logger.warning("fleet candidate generation %s rejected: %s",
                       candidate.generation, reason)

    # -- the sidecar canary ----------------------------------------------
    def _probe_cmd(self, bundle_path: str) -> List[str]:
        cmd = PROBE_CLI + [
            "probe", "--bundle", bundle_path,
            "--data", self.canary_data,
            "--samples", str(self.canary_samples),
            "--seed", str(self.canary_seed),
        ]
        if self.canary_feature != "raw":
            # the feature space is pinned to the boot incumbent's bundle
            # (NOT the rolling self.bundle_path): cached incumbent probes
            # stay comparable with every later candidate probe
            cmd += ["--feature", self.canary_feature,
                    "--feature-bundle",
                    self._feature_bundle or self.bundle_path]
        return cmd

    def _sidecar_probe(self, bundle_path: str) -> dict:
        """Probe a bundle's quality in a sidecar subprocess — the serving
        workers never pay the probe's compiles or its device time."""
        out = subprocess.run(
            self._probe_cmd(bundle_path), capture_output=True, text=True,
            timeout=self.probe_timeout_s, env=self._env,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"probe exited rc={out.returncode}: "
                f"{(out.stderr or out.stdout).strip()[-500:]}")
        try:
            return json.loads(out.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError) as exc:
            raise RuntimeError(
                f"probe wrote no JSON: {exc}; stdout={out.stdout[-500:]!r}")

    def _incumbent_probe(self) -> Optional[dict]:
        """The baseline probe, cached per generation. None means the
        incumbent bundle no longer exists on disk AND no probe was ever
        cached — there is no baseline and never will be (the caller rolls
        ungated rather than wedging the fleet)."""
        gen = self.generation
        probe = self._incumbent_probes.get(gen)
        if probe is None:
            if self.bundle_path is None or not os.path.isdir(self.bundle_path):
                return None
            probe = self._sidecar_probe(self.bundle_path)
            self._incumbent_probes = {gen: probe}
        return probe


def _free_port(host: str = "127.0.0.1") -> int:
    import socket

    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]
