"""fleet/ — fault-tolerant multi-process serving.

PR 7 made one serving process survive model updates; this package makes
the serving PLANE survive processes. One stdlib router fronts N worker
processes (each a ``python -m gan_deeplearning4j_tpu.serving`` instance),
and three cooperating pieces keep every submitted request answered
exactly once while workers die, hang, warm, and upgrade underneath it —
the fault-tolerance-as-design-axis argument of the TensorFlow system
paper (PAPERS.md), applied to the serve side:

- :mod:`.router` — power-of-two-choices proxying over scraped worker
  ``/metrics``, per-request timeouts, and a token-bucket retry budget
  (shed/connect-failed attempts retry on a different worker with
  exponential backoff + jitter; an exhausted budget answers an honest
  503, never a retry storm);
- :mod:`.health` — active ``/healthz`` probing plus passive outlier
  ejection: consecutive failures or a windowed error rate trip a
  per-worker circuit breaker (ejected → half-open → one probe →
  re-admitted), so a SIGKILLed, hung, warming, or draining worker leaves
  and rejoins the pool without operator action;
- :mod:`.manager` — process lifecycle: spawn from a shared checkpoint
  store, relaunch on death (spawn failures back off, never a hot loop),
  force-restart on hang, **draining restarts** (unroute → drain via
  ``/metrics`` → SIGTERM → relaunch → re-admit warm), and rolling
  generation upgrades admitted by ONE fleet-level canary decision
  (sidecar probes + ``deploy.compare_probes``), with halt-and-quarantine
  on regression;
- :mod:`.autoscaler` — the SLO-driven elastic control loop: resize the
  fleet between min and max against burn-rate/queue/occupancy signals
  (hysteresis + cooldowns, fail-closed holds on missing data), scale
  down only through the drain path, and at max size under sustained
  overload enter tiered **brownout** admission control at the router
  instead of falling over.

``python -m gan_deeplearning4j_tpu.fleet`` runs the whole plane;
``scripts/fleet_drill.py`` proves the invariants against real faults.
Architecture notes: docs/FLEET.md.
"""

from gan_deeplearning4j_tpu.fleet.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
)
from gan_deeplearning4j_tpu.fleet.health import (
    ADMITTABLE,
    CircuitBreaker,
    probe_worker,
)
from gan_deeplearning4j_tpu.fleet.manager import (
    FleetManager,
    WorkerProcess,
    WorkerSlot,
)
from gan_deeplearning4j_tpu.fleet.router import (
    FleetRouter,
    NoWorkerAvailable,
    RetryBudget,
    WorkerRef,
    make_router_server,
    scrape_metrics,
)

__all__ = [
    "ADMITTABLE",
    "Autoscaler",
    "AutoscalerConfig",
    "CircuitBreaker",
    "FleetManager",
    "FleetRouter",
    "NoWorkerAvailable",
    "RetryBudget",
    "WorkerProcess",
    "WorkerRef",
    "WorkerSlot",
    "make_router_server",
    "probe_worker",
    "scrape_metrics",
]
