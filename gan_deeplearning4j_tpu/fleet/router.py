"""FleetRouter — the multi-process serving front end.

One stdlib ``ThreadingHTTPServer`` proxies ``/v1/*`` to N serving worker
processes. Three mechanisms make the fleet tolerate what a single process
cannot (docs/FLEET.md):

- **power-of-two-choices routing** — each request picks two random
  routable workers and takes the less loaded one (local in-flight count
  plus the queue depth scraped from the worker's ``/metrics``). P2C gets
  most of the benefit of full least-loaded routing without herding every
  request onto one briefly-idle worker between scrapes.
- **retry budget** — a shed (worker 503) or connect-failed attempt is
  retried on a *different* worker with exponential backoff + jitter, but
  only while the token bucket holds a token (deposits accrue per proxied
  request at ``retry_ratio``, capped at ``retry_burst``). Budget
  exhausted ⇒ an honest 503 — under a fleet-wide brownout the router
  amplifies load by at most ``1 + retry_ratio``, never a retry storm.
- **health ejection** — every proxied outcome feeds the worker's
  :class:`~.health.CircuitBreaker` (passive), and a health loop probes
  ``/healthz`` actively (admission, half-open re-admission) and scrapes
  ``/metrics`` (load + liveness). A SIGKILLed, hung, warming, or
  draining worker silently leaves the pool and rejoins when healthy.
- **brownout admission control** — at the autoscaler's max size under
  sustained overload (docs/FLEET.md "Brownout") the router degrades in
  a chosen order instead of collapsing: tier 1 sheds oversized
  ``sample`` slabs with an honest 503, tier 2 additionally caps
  effective deadlines; the state is explicit in ``/healthz``
  (``"brownout"``) and the ``fleet_brownout`` gauge.

Exactly-one-answer is the router's contract: every accepted request gets
exactly one HTTP response — success, the worker's own non-retryable
answer, or an honest 503. A timed-out attempt may still execute on the
worker (inference is idempotent; the client gets the retry's answer).

The router never touches model bytes and adds no serve-time compiles —
the bounded-compile invariant is per worker and re-routing cannot break
it (the drill asserts each worker's ``serve_compile_counts`` stays 0).
Every network call here carries an explicit timeout — jaxlint JG017
polices that on this path.

The router is also the fleet's observability edge (docs/OBSERVABILITY.md
"Fleet observability"): it stamps/adopts an ``X-Trace-Id`` per request
and forwards it on every worker attempt (one causal chain across
retries), serves the merged fleet registry at ``GET /metrics?scope=fleet``
(JSON + Prometheus) and the merged fleet span trace at
``GET /debug/trace``, and feeds every routed outcome into the SLO
burn-rate tracker surfaced in ``/healthz``.
"""

from __future__ import annotations

import http.client
import json
import logging
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from gan_deeplearning4j_tpu.fleet.health import (
    CircuitBreaker,
    http_json,
    probe_worker,
)
from gan_deeplearning4j_tpu.telemetry.aggregate import (
    json_sanitize,
    merge_snapshots,
    merge_traces,
    snapshot_to_prometheus,
)
from gan_deeplearning4j_tpu.telemetry.registry import get_registry
from gan_deeplearning4j_tpu.telemetry.slo import SLOConfig, SLOTracker
from gan_deeplearning4j_tpu.telemetry.trace import (
    TRACER,
    bind_trace_id,
    current_trace_id,
    new_trace_id,
    sanitize_trace_id,
    unbind_trace_id,
)

logger = logging.getLogger(__name__)


class RetryBudget:
    """Token bucket bounding fleet-wide retry amplification. Each proxied
    request deposits ``ratio`` tokens (capped at ``burst``); each retry
    spends one. Starts full so a cold router can absorb a worker death
    immediately."""

    def __init__(self, ratio: float = 0.2, burst: float = 10.0):
        if ratio < 0 or burst < 1:
            raise ValueError("retry ratio must be >= 0 and burst >= 1")
        self.ratio = ratio
        self.burst = float(burst)
        self._tokens = float(burst)
        self._lock = threading.Lock()

    def deposit(self) -> None:
        with self._lock:
            self._tokens = min(self.burst, self._tokens + self.ratio)

    def spend(self) -> bool:
        """Take one token; False means the budget is exhausted and the
        caller must answer 503 instead of retrying."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def refund(self) -> None:
        """Return a spent token (capped at burst): a retry that found no
        worker to land on never amplified load, so it must not count
        against requests whose retry WOULD reach a live worker."""
        with self._lock:
            self._tokens = min(self.burst, self._tokens + 1.0)

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class WorkerRef:
    """The router's view of one worker process."""

    def __init__(self, worker_id: str, base_url: str, *, pid=None,
                 breaker: Optional[CircuitBreaker] = None):
        self.id = worker_id
        self.base_url = base_url.rstrip("/")
        self.pid = pid
        self.breaker = breaker or CircuitBreaker()
        self.draining = False
        self._lock = threading.Lock()
        self._inflight = 0  # requests this router is running there NOW
        self._scraped: dict = {}  # last /metrics snapshot (queue, gen, ...)
        self.counts = {"ok": 0, "shed": 0, "failed": 0}

    # -- load accounting (p2c input) -------------------------------------
    def begin(self) -> None:
        with self._lock:
            self._inflight += 1

    def end(self) -> None:
        with self._lock:
            self._inflight -= 1

    def count(self, outcome: str) -> None:
        """Record one proxied-attempt outcome ("ok"/"shed"/"failed")."""
        with self._lock:
            self.counts[outcome] += 1

    @property
    def load(self) -> int:
        with self._lock:
            scraped = self._scraped
            return (self._inflight
                    + int(scraped.get("queue_depth", 0))
                    + int(scraped.get("in_flight", 0)))

    def update_scrape(self, metrics: dict) -> None:
        with self._lock:
            self._scraped = {
                "queue_depth": metrics.get("queue_depth", 0),
                "in_flight": metrics.get("pipeline", {}).get("in_flight", 0),
                "generation": metrics.get("generation"),
                "draining": metrics.get("draining", False),
                "serve_compile_counts": metrics.get("engine", {}).get(
                    "serve_compile_counts", {}),
                "at": time.monotonic(),
            }

    @property
    def generation(self):
        with self._lock:
            return self._scraped.get("generation")

    @property
    def routable(self) -> bool:
        with self._lock:
            # the worker's own /metrics "draining" flag: a worker drained
            # directly (POST /admin/drain, not through the manager) must
            # leave the pool too, not keep receiving /v1 traffic its
            # pipeline will never empty of
            self_drained = bool(self._scraped.get("draining", False))
        return (self.breaker.routable and not self.draining
                and not self_drained)

    def snapshot(self) -> dict:
        with self._lock:
            scraped = dict(self._scraped)
            inflight = self._inflight
            counts = dict(self.counts)
        scraped_at = scraped.get("at")
        return {
            "id": self.id,
            "base_url": self.base_url,
            "pid": self.pid,
            "draining": self.draining,
            "breaker": self.breaker.snapshot(),
            "routable": self.routable,
            "inflight": inflight,
            "generation": scraped.get("generation"),
            "queue_depth": scraped.get("queue_depth"),
            # scrape staleness: a wedged /metrics endpoint shows up HERE
            # (the age climbing past the probe interval) before the
            # breaker's failure streak ever trips; None = never scraped
            # since (re)launch
            "last_scrape_age_s": (
                round(time.monotonic() - scraped_at, 3)
                if scraped_at is not None else None),
            "counts": counts,
        }


class NoWorkerAvailable(RuntimeError):
    """Every worker is ejected, draining, or already tried."""


class FleetRouter:
    """Routing + health state over a set of :class:`WorkerRef`. The HTTP
    front end (:func:`make_router_server`) and the drill both drive
    :meth:`handle`; the manager registers/ejects/drains workers."""

    def __init__(self, *, request_timeout: float = 10.0,
                 probe_timeout: float = 2.0, probe_interval: float = 0.25,
                 retry_ratio: float = 0.2, retry_burst: float = 10.0,
                 max_attempts: int = 3, backoff_base: float = 0.02,
                 backoff_max: float = 0.25, seed: int = 0,
                 breaker_kwargs: Optional[dict] = None,
                 slo_config: Optional[SLOConfig] = None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.request_timeout = request_timeout
        self.probe_timeout = probe_timeout
        self.probe_interval = probe_interval
        self.max_attempts = max_attempts
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.budget = RetryBudget(retry_ratio, retry_burst)
        self._breaker_kwargs = breaker_kwargs or {}
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._workers: Dict[str, WorkerRef] = {}
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self.manager = None  # FleetManager, when attached (POST /admin/poll)
        self.started_at = time.time()
        # brownout: tiered admission control the autoscaler engages when
        # the fleet is at max size and still overloaded (docs/FLEET.md
        # "Brownout"). 0 = off, 1 = shed oversized sample slabs, 2 = also
        # cap effective deadlines.
        self._brownout_level = 0
        self._brownout_max_rows = 32
        self._brownout_deadline_s = 1.0
        # -- counters ----------------------------------------------------
        self._counts = {"proxied": 0, "ok": 0, "error": 0, "retries": 0,
                        "budget_exhausted": 0, "no_worker": 0,
                        "attempts_exhausted": 0, "ejections": 0,
                        "brownout_shed": 0}
        registry = get_registry()
        self._c_requests = registry.counter(
            "fleet_requests_total", "router request outcomes",
            labelnames=("outcome",))
        self._c_retries = registry.counter(
            "fleet_retries_total", "attempts re-routed to another worker")
        self._c_exhausted = registry.counter(
            "fleet_retry_budget_exhausted_total",
            "requests answered 503 because the retry budget was empty")
        self._c_ejections = registry.counter(
            "fleet_ejections_total", "circuit-breaker trips across workers")
        self._g_routable = registry.gauge(
            "fleet_workers_routable", "workers currently in the routable pool")
        self._g_brownout = registry.gauge(
            "fleet_brownout",
            "brownout tier (0 = off, 1 = large sample slabs shed, "
            "2 = + effective deadlines capped)")
        self._g_brownout.set(0.0)
        self._c_brownout_sheds = registry.counter(
            "fleet_brownout_sheds_total",
            "requests shed by brownout admission control",
            labelnames=("tier",))
        self._c_brownout_clamps = registry.counter(
            "fleet_brownout_deadline_clamps_total",
            "admitted requests whose effective deadline was capped by "
            "tier-2 brownout")
        # per-member liveness/staleness as REAL gauge families (not just
        # /healthz JSON): what the prom surface scrapes and the alert
        # rules evaluate. Families cost nothing until labeled; the
        # health loop materializes one series per registered member and
        # remove_worker drops it (a retired member is not a down member)
        self._g_member_routable = registry.gauge(
            "fleet_member_routable",
            "1 when the member is in the routable pool, 0 when ejected, "
            "draining, or dead",
            labelnames=("worker",))
        self._g_member_scrape_age = registry.gauge(
            "fleet_member_scrape_age_seconds",
            "age of the member's last successful /metrics scrape "
            "(NaN until the first lands)",
            labelnames=("worker",))
        # alert plane (telemetry/alerts.py) — None until attach_alerts;
        # disabled it allocates zero series and zero per-request work
        # (the PR 6 telemetry-off contract)
        self.alerts = None
        self._exemplars = None
        self._h_latency: Optional[object] = None
        self._g_pressure: Optional[object] = None
        # SLO burn-rate tracking over every routed outcome — the healthz
        # block and the admission signal (telemetry/slo.py)
        self.slo = SLOTracker(slo_config)

    # -- worker registry -------------------------------------------------
    def add_worker(self, worker_id: str, base_url: str, pid=None
                   ) -> WorkerRef:
        ref = WorkerRef(worker_id, base_url, pid=pid,
                        breaker=CircuitBreaker(**self._breaker_kwargs))
        with self._lock:
            self._workers[worker_id] = ref
        return ref

    def remove_worker(self, worker_id: str) -> None:
        with self._lock:
            self._workers.pop(worker_id, None)
        # a retired member must not linger as a zero-valued "down" fact
        # (the worker_down rule would page forever on a scale-down)
        self._g_member_routable.remove(worker=worker_id)
        self._g_member_scrape_age.remove(worker=worker_id)

    def worker(self, worker_id: str) -> WorkerRef:
        with self._lock:
            return self._workers[worker_id]

    def workers(self) -> List[WorkerRef]:
        with self._lock:
            return list(self._workers.values())

    def mark_draining(self, worker_id: str, draining: bool = True) -> None:
        """Manager-side drain mark: the worker leaves the routable pool
        immediately; in-flight proxied requests still finish."""
        self.worker(worker_id).draining = draining

    # -- selection -------------------------------------------------------
    def _pick(self, exclude: set) -> WorkerRef:
        candidates = [w for w in self.workers()
                      if w.routable and w.id not in exclude]
        if not candidates:
            raise NoWorkerAvailable(
                "no routable worker (all ejected, draining, or tried)")
        if len(candidates) == 1:
            return candidates[0]
        with self._lock:  # Random() is not thread-safe
            a, b = self._rng.sample(candidates, 2)
        return a if a.load <= b.load else b

    # -- brownout admission control --------------------------------------
    @property
    def brownout_level(self) -> int:
        with self._lock:
            return self._brownout_level

    def set_brownout(self, level: int, max_rows: Optional[int] = None,
                     deadline_s: Optional[float] = None) -> None:
        """Set the brownout tier (clamped to 0..2); ``max_rows`` /
        ``deadline_s`` override the admission parameters when given.
        Driven by the autoscaler at max size under sustained overload —
        degradation becomes ordered and observable (``/healthz``
        ``brownout`` block, ``fleet_brownout`` gauge) instead of
        emergent queue collapse."""
        level = max(0, min(2, int(level)))
        with self._lock:
            self._brownout_level = level
            if max_rows is not None:
                self._brownout_max_rows = int(max_rows)
            if deadline_s is not None:
                self._brownout_deadline_s = float(deadline_s)
        self._g_brownout.set(float(level))
        logger.warning("brownout tier set to %d", level)

    def _brownout_admit(self, path: str, body: Optional[bytes]
                        ) -> Tuple[Optional[bytes], Optional[bytes]]:
        """Tiered admission under brownout: ``(body, shed)``. A non-None
        ``shed`` is the 503 payload for a tier-1 rejection (oversized
        ``sample`` slab — the largest single cost one request can
        impose); otherwise ``body`` may come back rewritten with a
        tier-2 effective-deadline cap. Malformed bodies pass through
        untouched — the worker's 400 is the client's answer, not ours."""
        with self._lock:
            level = self._brownout_level
            max_rows = self._brownout_max_rows
            deadline = self._brownout_deadline_s
        if level < 1 or body is None or not path.startswith("/v1/"):
            return body, None
        try:
            payload = json.loads(body)
        except ValueError:
            return body, None
        if not isinstance(payload, dict):
            return body, None
        data = payload.get("data")
        # row counting mirrors the worker's shape rules: a flat 1-D list
        # is ONE row (service.py reshapes it), not len(data) rows — a
        # single wide sample must never be shed as a slab
        if not isinstance(data, list) or not data:
            rows = 0
        elif isinstance(data[0], (list, tuple)):
            rows = len(data)
        else:
            rows = 1
        if path.startswith("/v1/sample") and rows > max_rows:
            self._c_brownout_sheds.labels(tier="large_slab").inc()
            return body, _json_body(
                "overloaded",
                f"brownout: sample slabs over {max_rows} rows are shed "
                f"until the fleet recovers (got {rows})")
        if level >= 2:
            timeout = payload.get("timeout")
            if timeout is not None and not isinstance(timeout, (int, float)):
                return body, None  # let the worker reject it with a 400
            if timeout is None or timeout > deadline:
                payload["timeout"] = deadline
                self._c_brownout_clamps.inc()
                body = json.dumps(payload).encode()
        return body, None

    # -- the proxy -------------------------------------------------------
    def _attempt(self, ref: WorkerRef, method: str, path: str,
                 body: Optional[bytes]) -> Tuple[int, bytes]:
        """One proxied attempt. Raises OSError-family on connection-level
        failure (dead/hung worker); returns the worker's (status, body)
        otherwise."""
        host, _, port = ref.base_url.rpartition("//")[2].partition(":")
        conn = http.client.HTTPConnection(host, int(port),
                                          timeout=self.request_timeout)
        headers = {"Content-Type": "application/json"}
        trace_id = current_trace_id()
        if trace_id is not None:
            # the propagation header: the worker's HTTP handler adopts it
            # into ITS correlation contextvar, so worker-side spans carry
            # the router's id — including a retry's second worker
            headers["X-Trace-Id"] = trace_id
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def handle(self, method: str, path: str, body: Optional[bytes],
               trace_id: Optional[str] = None) -> Tuple[int, bytes]:
        """Route one ``/v1/*`` request: p2c pick, proxy, retry shed and
        connect-failed attempts on a different worker under the budget.
        Always returns exactly one response.

        ``trace_id`` is the client's ``X-Trace-Id`` (adopted when valid,
        else a fresh id is minted). The id is bound to this thread's
        correlation contextvar — the router's own route/attempt/retry
        spans pick it up — and forwarded to every worker attempt, so one
        request's spans merge into one causal chain across the router and
        every worker it was tried on, retries included. The final outcome
        and latency also feed the SLO tracker (5xx = availability
        failure; latency is measured on answered requests only)."""
        tid = sanitize_trace_id(trace_id) or new_trace_id()
        token = bind_trace_id(tid)
        t0 = time.perf_counter()
        status = 500  # an exception out of _route IS a 500 for the SLO:
        # the HTTP front end's catch-all answers the client 500, and the
        # burn rate must see it — a router-side 500-storm that bypassed
        # the tracker would leave fleet_slo_ok reporting healthy
        try:
            if TRACER.enabled:
                with TRACER.span("fleet.route", path=path):
                    status, payload = self._route(method, path, body)
            else:
                status, payload = self._route(method, path, body)
            return status, payload
        finally:
            unbind_trace_id(token)
            latency = time.perf_counter() - t0
            self.slo.record(status < 500,
                            latency if status < 500 else None)
            if self._exemplars is not None:
                # evidence for the alert plane: the trace ids of concrete
                # requests that crossed a bad threshold, linkable into
                # the merged GET /debug/trace chain
                if status >= 500:
                    self._exemplars.record("availability", tid,
                                           status=status)
                else:
                    self._h_latency.observe(latency)
                    if latency > self.slo.config.latency_threshold_s:
                        self._exemplars.record(
                            "latency", tid, status=status,
                            latency_ms=round(latency * 1e3, 3))

    def _route(self, method: str, path: str, body: Optional[bytes]
               ) -> Tuple[int, bytes]:
        self.budget.deposit()
        with self._lock:
            self._counts["proxied"] += 1
        body, shed = self._brownout_admit(path, body)
        if shed is not None:
            # an ordered, honest 503 — observable in the counters and in
            # the SLO burn (handle() records every 5xx), never a retry
            with self._lock:
                self._counts["brownout_shed"] += 1
                self._counts["error"] += 1
            self._c_requests.labels(outcome="brownout_shed").inc()
            return 503, shed
        tried: set = set()
        retryable: Optional[str] = None
        for attempt in range(self.max_attempts):
            if attempt > 0:
                if not self.budget.spend():
                    with self._lock:
                        self._counts["budget_exhausted"] += 1
                        self._counts["error"] += 1
                    self._c_exhausted.inc()
                    self._c_requests.labels(outcome="budget_exhausted").inc()
                    return 503, _json_body(
                        "overloaded",
                        f"retry budget exhausted after {retryable}")
                with self._lock:
                    self._counts["retries"] += 1
                    jitter = 0.5 + self._rng.random() * 0.5
                self._c_retries.inc()
                if TRACER.enabled:
                    TRACER.instant("fleet.retry", {
                        "attempt": attempt, "reason": retryable})
                delay = min(self.backoff_max,
                            self.backoff_base * (2 ** (attempt - 1)))
                time.sleep(delay * jitter)
            try:
                ref = self._pick(tried)
            except NoWorkerAvailable as exc:
                # fast 503, never a hang: an all-ejected fleet answers in
                # O(1) instead of blocking clients on dead sockets
                if attempt > 0:
                    # the spent token bought no retry — refund it, or a
                    # brownout with one survivor drains the shared bucket
                    # on retries that never happen
                    self.budget.refund()
                with self._lock:
                    self._counts["no_worker"] += 1
                    self._counts["error"] += 1
                self._c_requests.labels(outcome="no_worker").inc()
                return 503, _json_body("overloaded", str(exc))
            tried.add(ref.id)
            ref.begin()
            t0 = time.perf_counter()
            try:
                status, payload = self._attempt(ref, method, path, body)
            except (OSError, http.client.HTTPException) as exc:
                # connection-level failure: the worker is gone or hung —
                # passive ejection signal, retryable on another worker
                retryable = f"{type(exc).__name__}: {exc}"
                if self._exemplars is not None:
                    # ref.pid is still the DEAD process here (the manager
                    # rebinds it at relaunch): the worker_down alert's
                    # exemplars name the pid that actually failed
                    self._exemplars.record(
                        "worker_failure", current_trace_id(),
                        worker=ref.id, pid=ref.pid,
                        error=type(exc).__name__)
                ref.count("failed")
                if ref.breaker.record(False) == "tripped":
                    self._note_ejection(ref, retryable)
                continue
            finally:
                ref.end()
                if TRACER.enabled:
                    TRACER.complete("fleet.attempt", t0, time.perf_counter(),
                                    {"worker": ref.id, "path": path,
                                     "attempt": attempt})
            if status == 503:
                # the worker answered but shed (overloaded/deadline):
                # alive for the breaker, retryable for the client
                retryable = f"worker {ref.id} shed (503)"
                ref.breaker.record(True)
                ref.count("shed")
                continue
            ref.breaker.record(True)
            ref.count("ok")
            with self._lock:
                self._counts["ok" if status < 400 else "error"] += 1
            self._c_requests.labels(
                outcome="ok" if status < 400 else "worker_error").inc()
            return status, payload
        # attempts exhausted on retryable failures
        with self._lock:
            self._counts["attempts_exhausted"] += 1
            self._counts["error"] += 1
        self._c_requests.labels(outcome="attempts_exhausted").inc()
        return 503, _json_body(
            "overloaded",
            f"all {self.max_attempts} attempts failed ({retryable})")

    def _note_ejection(self, ref: WorkerRef, reason: str) -> None:
        with self._lock:
            self._counts["ejections"] += 1
        self._c_ejections.inc()
        logger.warning("worker %s ejected: %s", ref.id, reason)

    # -- the health loop -------------------------------------------------
    def start_health_loop(self) -> threading.Thread:
        with self._lock:
            if (self._health_thread is not None
                    and self._health_thread.is_alive()):
                return self._health_thread
            self._stop.clear()
            t = threading.Thread(target=self._health_loop,
                                 name="fleet-health", daemon=True)
            self._health_thread = t
        t.start()
        return t

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._health_thread
        if t is not None:
            t.join(timeout)

    def health_pass(self) -> None:
        """One probe/scrape sweep over every worker (the loop body, also
        driven directly by tests and the manager's wait paths)."""
        for ref in self.workers():
            if ref.breaker.probe_due():
                ok, _ = probe_worker(ref.base_url, timeout=self.probe_timeout)
                if ref.breaker.probe_result(ok) == "admitted":
                    logger.info("worker %s admitted to the pool", ref.id)
                continue
            if not ref.breaker.routable:
                continue  # open: wait out the backoff, probe when half-open
            metrics = scrape_metrics(ref.base_url, timeout=self.probe_timeout)
            if metrics is None:
                # a hung worker with no traffic still gets ejected: the
                # scrape IS the passive signal then
                if ref.breaker.record(False) == "tripped":
                    self._note_ejection(ref, "metrics scrape failed")
            else:
                # a successful scrape is NOT recorded as a passive
                # success: a worker whose /v1 path is wedged but whose
                # HTTP server still answers /metrics must not have its
                # proxied-failure streak washed out by scrape successes
                ref.update_scrape(metrics)
        self._g_routable.set(sum(1 for w in self.workers() if w.routable))
        if self.alerts is not None:
            # the evaluation tick rides the sweep this loop already ran —
            # alerting shares the scrape, it never adds one
            try:
                self.alerts.evaluate(self.alert_view())
            except Exception:
                logger.exception("alert evaluation failed")
        else:
            self.member_signals()  # keep the member gauges fresh anyway

    def _health_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.health_pass()
            except Exception:  # a probe bug must not kill the loop
                logger.exception("health pass failed")
            self._stop.wait(self.probe_interval)

    # -- the alert plane (telemetry/alerts.py) ----------------------------
    def attach_alerts(self, manager) -> None:
        """Attach an :class:`~...telemetry.alerts.AlertManager`: the
        health loop ticks its evaluation over :meth:`alert_view`, the
        request path starts feeding the latency histogram and exemplar
        store, and ``GET /alerts`` + the ``/healthz`` block go live.
        Never attached, none of those series or ring buffers exist."""
        registry = get_registry()
        self._h_latency = registry.histogram(
            "fleet_request_latency_seconds",
            "client-visible latency of answered (non-5xx) routed "
            "requests — the latency-anomaly rule's input (bounded "
            "samples, so the p99 tracks recent behavior)",
            max_samples=512)
        self._g_pressure = registry.gauge(
            "fleet_pressure",
            "queue+in-flight per routable worker (NaN when none is "
            "routable — fail closed)")
        self._exemplars = manager.exemplars
        self.alerts = manager

    def annotate_member(self, labels: dict) -> dict:
        """Annotation hook for member-scoped rules: worker id -> the
        facts an operator reaches for first (pid, url, breaker state)."""
        try:
            ref = self.worker(str(labels.get("worker")))
        except KeyError:
            return {}
        return {"pid": ref.pid, "base_url": ref.base_url,
                "breaker": ref.breaker.snapshot().get("state")}

    def member_signals(self) -> dict:
        """One pass over the health loop's already-scraped worker state:
        routable/queue/in-flight totals plus per-member staleness — and
        the refresh of the ``fleet_member_*`` (and, with the alert plane
        attached, ``fleet_pressure``) gauges. THE shared seam: the
        autoscaler's tick and the alert evaluator both read this instead
        of paying a second per-worker HTTP fan-out."""
        routable = queue = inflight = 0
        ages: Dict[str, Optional[float]] = {}
        for ref in self.workers():
            snap = ref.snapshot()
            up = bool(snap["routable"])
            if up:
                routable += 1
            queue += int(snap.get("queue_depth") or 0)
            inflight += int(snap.get("inflight") or 0)
            age = snap.get("last_scrape_age_s")
            ages[ref.id] = age
            self._g_member_routable.labels(worker=ref.id).set(
                1.0 if up else 0.0)
            self._g_member_scrape_age.labels(worker=ref.id).set(
                float("nan") if age is None else float(age))
        # reconcile: a tick racing remove_worker can re-create a retired
        # member's series AFTER the removal (list snapshotted above) —
        # and with the ref gone, nothing would ever touch it again, so
        # worker_down would page forever on a scale-down. Prune any
        # series whose member is no longer registered.
        for fam in (self._g_member_routable, self._g_member_scrape_age):
            for labels, _ in fam.series():
                if labels.get("worker") not in ages:
                    fam.remove(**labels)
        if self._g_pressure is not None:
            self._g_pressure.set(
                ((queue + inflight) / routable) if routable
                else float("nan"))
        return {"routable": routable, "queue_depth": queue,
                "in_flight": inflight, "scrape_age_s": ages}

    def alert_view(self) -> dict:
        """The alert evaluator's input: the same snapshot-shaped payload
        ``GET /metrics?scope=fleet`` is built from, assembled purely
        from signals already in this process (the router/manager/
        autoscaler registry plus the health loop's member scrapes,
        refreshed through :meth:`member_signals`) — evaluation adds no
        second per-worker HTTP fan-out. The evaluator is snapshot-shape
        generic, so it also consumes an actual merged fleet snapshot
        unchanged (tested)."""
        self.slo.snapshot()  # refresh the burn-rate gauges
        self.member_signals()
        return get_registry().snapshot(include_samples=True)

    # -- fleet-scale observability ---------------------------------------
    def fleet_metrics_snapshot(self) -> dict:
        """``GET /metrics?scope=fleet`` — fan out to every registered
        worker's ``/metrics?scope=registry`` (samples included, so merged
        histogram percentiles keep the nearest-rank contract), merge with
        this router process's own registry, and return ONE snapshot.
        A worker that fails to answer becomes a labeled gap
        (``fleet_member_up{worker=...} 0``), never an error."""
        self.slo.snapshot()  # refresh the burn-rate gauges into the scrape
        parts: Dict[str, dict] = {}
        gaps: List[str] = []
        member_labels: Dict[str, dict] = {}
        for ref, snap in self._fan_out("/metrics?scope=registry"):
            if isinstance(snap, dict) and snap:
                parts[ref.id] = snap
                # the model/generation dimension: two workers serving
                # different generations (mid-roll, or a mux fleet) must
                # not have their per-model counter series summed into
                # one — the worker's scraped generation labels every
                # series it contributes (docs/MULTIPLEX.md)
                gen = ref.generation
                if gen is not None:
                    member_labels[ref.id] = {"generation": str(gen)}
            else:
                gaps.append(ref.id)
        parts["router"] = get_registry().snapshot(include_samples=True)
        return merge_snapshots(parts, gaps=gaps,
                               member_labels=member_labels)

    def _fan_out(self, path: str):
        """Concurrent bounded GETs of ``path`` on every registered worker:
        [(ref, json_or_None)]. Concurrency matters — sequentially, K
        unreachable workers would cost K × probe_timeout per fleet scrape;
        fanned out the whole sweep is bounded by ~one probe_timeout."""
        refs = self.workers()
        if not refs:
            return []
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(min(8, len(refs))) as pool:
            return list(zip(refs, pool.map(
                lambda ref: http_json(f"{ref.base_url}{path}",
                                      timeout=self.probe_timeout),
                refs)))

    def fleet_trace(self) -> dict:
        """``GET /debug/trace`` — ONE Chrome/Perfetto trace for the whole
        fleet: this process's spans plus every worker's ``/debug/spans``,
        concatenated (valid because every tracer pins timestamps to the
        wall epoch and stamps its own pid — each process renders as its
        own track, and one trace id threads a request across them)."""
        docs: Dict[str, Optional[dict]] = {
            "router": TRACER.chrome_trace({"source": "fleet.router"}),
        }
        for ref, doc in self._fan_out("/debug/spans"):
            docs[ref.id] = doc
        return merge_traces(docs, metadata={"source": "fleet"})

    # -- observability ---------------------------------------------------
    def healthz(self) -> dict:
        workers = [w.snapshot() for w in self.workers()]
        routable = [w for w in workers if w["routable"]]
        generations = sorted({w["generation"] for w in routable
                              if w["generation"] is not None})
        with self._lock:
            level = self._brownout_level
            max_rows = self._brownout_max_rows
            deadline = self._brownout_deadline_s
        # "brownout" outranks "ok": the fleet is serving, but degraded —
        # by design, not by accident — and a dashboard must say so
        status = ("down" if not routable
                  else "brownout" if level > 0 else "ok")
        body = {
            "status": status,
            "brownout": {"active": level > 0, "level": level,
                         "max_sample_rows": max_rows,
                         "deadline_cap_s": deadline},
            "role": "router",
            "workers": workers,
            "routable": len(routable),
            # the fleet generation: the one every routable worker agrees
            # on, else None (mid-roll)
            "generation": generations[0] if len(generations) == 1 else None,
            "generations": generations,
            # burn rates + the fail-closed admission signal — informational
            # here ("status" stays routability-driven); the autoscaler and
            # upgrade gate read slo["ok"]
            "slo": self.slo.snapshot(),
        }
        if self.alerts is not None:
            # the compact "is anything firing" block; GET /alerts has the
            # full instances/exemplars/incidents payload
            body["alerts"] = self.alerts.health_block()
        if self.manager is not None:
            body["fleet"] = self.manager.status()
        return body

    def metrics(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            level = self._brownout_level
        return {
            **counts,
            "retry_budget_tokens": self.budget.tokens,
            "brownout_level": level,
            "slo": self.slo.snapshot(),
            "workers": [w.snapshot() for w in self.workers()],
        }


def _json_body(status: str, error: str) -> bytes:
    return json.dumps({"status": status, "error": error}).encode()


def scrape_metrics(base_url: str, timeout: float = 2.0) -> Optional[dict]:
    """One bounded ``GET /metrics`` scrape; None on any failure."""
    return http_json(f"{base_url}/metrics", timeout=timeout)


# -- HTTP front end ---------------------------------------------------------

class _RouterHandler(BaseHTTPRequestHandler):
    router: FleetRouter = None  # bound by make_router_server

    def _respond(self, status: int, body: bytes,
                 content_type: str = "application/json",
                 extra_headers: Optional[dict] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (extra_headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server naming contract)
        try:
            route, _, query = self.path.partition("?")
            params = parse_qs(query) if query else {}
            if route == "/healthz":
                self._respond(200, json.dumps(self.router.healthz()).encode())
            elif route == "/metrics":
                if params.get("scope", [""])[0] == "fleet":
                    snap = self.router.fleet_metrics_snapshot()
                    if "prom" in params.get("format", []):
                        self._respond(
                            200, snapshot_to_prometheus(snap).encode(),
                            content_type="text/plain; version=0.0.4; "
                                         "charset=utf-8")
                    else:
                        # NaN (empty-window SLO gauges) → null: strict
                        # JSON parsers reject a literal NaN token
                        self._respond(200, json.dumps(
                            json_sanitize(snap)).encode())
                else:
                    self._respond(200,
                                  json.dumps(self.router.metrics()).encode())
            elif route == "/debug/trace":
                # the merged fleet trace (router spans + every worker's
                # /debug/spans) as one Perfetto-loadable document
                self._respond(200,
                              json.dumps(self.router.fleet_trace()).encode())
            elif route == "/alerts":
                if self.router.alerts is None:
                    self._respond(404, _json_body(
                        "error", "no alert plane (start with --alerts)"))
                elif "prom" in params.get("format", []):
                    self._respond(
                        200, self.router.alerts.to_prometheus().encode(),
                        content_type="text/plain; version=0.0.4; "
                                     "charset=utf-8")
                else:
                    self._respond(200, json.dumps(json_sanitize(
                        self.router.alerts.snapshot())).encode())
            else:
                self._respond(404, _json_body("error",
                                              f"no route GET {route}"))
        except Exception as exc:  # a handler bug must answer, not reset
            logger.exception("GET %s failed", self.path)
            self._respond(500, _json_body(
                "error", f"{type(exc).__name__}: {exc}"))

    def do_POST(self):  # noqa: N802
        try:
            route, _, query = self.path.partition("?")
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else None
            if route.startswith("/v1/"):
                # adopt the client's trace id (sanitized) or mint one HERE
                # so the response can echo the id the spans carry
                tid = (sanitize_trace_id(self.headers.get("X-Trace-Id"))
                       or new_trace_id())
                status, payload = self.router.handle(
                    "POST", self.path, body, trace_id=tid)
                self._respond(status, payload,
                              extra_headers={"X-Trace-Id": tid})
                return
            if route == "/admin/poll" and self.router.manager is not None:
                params = parse_qs(query) if query else {}
                wait = params.get("block", ["0"])[0] not in ("0", "",
                                                             "false")
                state = self.router.manager.poll_now(wait=wait)
                self._respond(200 if wait else 202, json.dumps(
                    {"status": "ok", "fleet": state}).encode())
                return
            self._respond(404, _json_body("error", f"no route POST {route}"))
        except Exception as exc:
            logger.exception("POST %s failed", self.path)
            self._respond(500, _json_body(
                "error", f"{type(exc).__name__}: {exc}"))

    def log_message(self, fmt, *args):  # route to logging, not stderr
        logger.debug("%s - %s", self.address_string(), fmt % args)


def make_router_server(router: FleetRouter, host: str = "127.0.0.1",
                       port: int = 8100) -> ThreadingHTTPServer:
    """Bind (but do not start) the router's HTTP front end; ``port=0``
    picks a free port (tests)."""
    handler = type("BoundRouterHandler", (_RouterHandler,),
                   {"router": router})
    return ThreadingHTTPServer((host, port), handler)
