"""QuantDenseLayer — the int8 post-training-quantized dense layer.

The serving-side replacement ``quant/variants.py`` swaps in for a
``DenseLayer``/``OutputLayer`` vertex of the discriminator-feature
classifier: weights live as int8 with a per-output-channel symmetric
scale (``w ≈ W_q * w_scale``), the activation scale is a *static* layer
field calibrated once at build time on the canary's fixed seeded probe
batch, and the forward pass is :func:`~...ops.linear.quant_dense`
(int8×int8 → int32 accumulate, one dequant multiply). Inputs and outputs
stay float — the wire contract and every downstream layer are unchanged.

This is an inference-only layer: ``init`` exists only so the graph
machinery can shape-check it (real parameters always come from
quantizing a trained float checkpoint), and there is no loss attachment —
a quantized graph is never trained, it is *built* from a trained one.

Registered with the ``nn`` layer registry at import (``register_layer``),
and lazily importable through ``layer_from_dict`` so a quantized bundle
round-trips in a process that never imported quant/ explicitly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp

from gan_deeplearning4j_tpu.nn.input_type import InputType
from gan_deeplearning4j_tpu.nn.layers import Layer, register_layer
from gan_deeplearning4j_tpu.ops import linear as linear_ops


@register_layer
@dataclasses.dataclass(frozen=True)
class QuantDenseLayer(Layer):
    """Int8 dense with per-channel weight scales and a calibrated static
    activation scale (module docstring)."""

    n_out: int = 0
    n_in: Optional[int] = None  # inferred from in_type when None
    #: activation quantization scale (x ≈ round(x / act_scale) * act_scale)
    #: — calibrated at build time, static in the compiled executable
    act_scale: float = 1.0

    def _n_in(self, in_type: InputType) -> int:
        return self.n_in if self.n_in is not None else in_type.features

    def init(self, key, in_type) -> Dict[str, jnp.ndarray]:
        n_in = self._n_in(in_type)
        return {
            "W_q": jnp.zeros((n_in, self.n_out), jnp.int8),
            "w_scale": jnp.ones((self.n_out,), jnp.float32),
            "b": jnp.zeros((self.n_out,), jnp.float32),
        }

    def apply(self, params, x, *, train: bool, rng=None):
        y = linear_ops.quant_dense(
            x, params["W_q"], params["w_scale"], params["b"],
            float(self.act_scale),
        )
        return self._act(y), None

    def output_type(self, in_type):
        return InputType.feed_forward(self.n_out)

    def param_roles(self):
        # w_scale is deliberately NOT a weight role: l2 penalties and
        # weight-sync maps must never touch quantization scales
        return {"W_q": "weight", "w_scale": "scale", "b": "bias"}
