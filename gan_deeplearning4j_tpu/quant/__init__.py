"""quant/ — real quantized serving variants with measured cost.

Three parts (docs/QUANT.md):

- **variant builders** (:mod:`.variants`): a published serving bundle in,
  a bf16 (params + compute end-to-end, generator/sampler) or int8
  (per-channel symmetric PTQ of the discriminator-feature classifier,
  activation scales calibrated on the canary's fixed seeded probe batch)
  bundle out — a normal bundle whose manifest declares ``precision`` and
  provenance, adopted by the existing store/watcher/reloader/mux
  machinery unchanged.
- **measured cost** (:mod:`.cost`): each built variant profiled on the
  live device ladder (per-bucket latency, resident param bytes, staged
  width) into a manifest ``cost`` block; the mux registry's residency
  eviction and brownout shed ordering rank by the measurement, with the
  operator-declared number kept only as the bootstrap default.
- **quality gating**: nothing new — the deploy canary gate's relative
  FID/accuracy thresholds (deploy/canary.py) police quantization loss at
  adoption; an over-degraded variant is rejected through the existing
  quarantine path, never served.

The int8 forward pass is :class:`~.layers.QuantDenseLayer`
(int8×int8→int32 with dequant at the matmul — outputs stay float).
"""

from gan_deeplearning4j_tpu.quant.cost import (
    manifest_cost,
    measure_bundle_cost,
    measure_engine_cost,
    write_cost_block,
)
from gan_deeplearning4j_tpu.quant.layers import QuantDenseLayer
from gan_deeplearning4j_tpu.quant.variants import (
    build_bf16_variant,
    build_int8_variant,
    calibrate_activation_scales,
    cast_params_bf16,
    default_calibration_rows,
    quantize_classifier,
    quantize_dense_params,
)

__all__ = [
    "QuantDenseLayer",
    "build_bf16_variant",
    "build_int8_variant",
    "calibrate_activation_scales",
    "cast_params_bf16",
    "default_calibration_rows",
    "quantize_classifier",
    "quantize_dense_params",
    "manifest_cost",
    "measure_bundle_cost",
    "measure_engine_cost",
    "write_cost_block",
]
