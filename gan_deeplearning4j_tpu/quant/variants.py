"""Quantized variant builders — real bf16/int8 serving bundles.

Both builders take a published serving bundle (``serving.json`` + serializer
checkpoints — the artifact ``GanExperiment.publish_for_serving`` writes) and
emit a NEW bundle of the same shape whose manifest declares ``precision``
plus calibration provenance (``quant`` block). A quantized variant is just
a bundle: the store, watcher, reloader, and mux registry adopt it through
the machinery they already have, and the canary gate polices its quality
loss at adoption exactly like any other candidate (docs/QUANT.md).

- :func:`build_bf16_variant` — params cast to bfloat16 end-to-end (the
  serializer's tagged-uint16 encoding round-trips them losslessly); the
  serving engine reads ``precision: "bf16"`` and traces its AOT
  executables under a bfloat16 compute scope, so the matmuls run on the
  MXU's bf16 path with f32 accumulation. Resident param bytes halve.
- :func:`build_int8_variant` — post-training quantization of the
  discriminator-feature classifier: every dense vertex is rebuilt as a
  :class:`~.layers.QuantDenseLayer` with per-output-channel symmetric
  int8 weights and an activation scale calibrated on a fixed seeded probe
  batch (the canary's batch when the caller passes it — same rows, same
  determinism). The generator checkpoint is copied byte-identical: int8
  PTQ is the classifier's trade, the sampler keeps its precision.

Calibration is deterministic by construction: the same probe rows through
the same float graph produce bit-identical activation maxima, hence
bit-identical scales — asserted by tests/test_quant.py.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Dict, Optional

import numpy as np

#: symmetric int8 range: one sign bit + 7 magnitude bits, -128 excluded so
#: the scale maps amax exactly onto ±127 (standard symmetric PTQ)
_QMAX = 127.0

#: floor for calibrated maxima — an all-zero activation (dead vertex)
#: must not produce a zero scale (division by zero at quantize time)
_AMAX_FLOOR = 1e-8

#: the canary gate's probe defaults (deploy/canary.py) — the fallback
#: calibration batch is drawn with the same seed and row count so a
#: builder without the canary's real rows still calibrates on the same
#: fixed seeded stream the gate probes with
CALIBRATION_SEED = 666
CALIBRATION_ROWS = 256


def read_bundle_manifest(directory: str) -> dict:
    with open(os.path.join(directory, "serving.json")) as fh:
        return json.load(fh)


def write_bundle_manifest(directory: str, manifest: dict) -> None:
    """Temp + atomic-rename manifest write (the harness publish idiom) —
    a watcher polling the directory can never observe a torn manifest."""
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(manifest, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, os.path.join(directory, "serving.json"))
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def default_calibration_rows(num_features: int,
                             num_rows: int = CALIBRATION_ROWS,
                             seed: int = CALIBRATION_SEED) -> np.ndarray:
    """The fallback probe batch: seeded uniform rows in [0, 1) — the range
    the reference pipeline scales real rows into. Callers holding the
    canary's actual evaluation rows should pass those instead."""
    rng = np.random.default_rng(seed)
    return rng.random((num_rows, num_features), dtype=np.float32)


# ---------------------------------------------------------------------------
# int8 PTQ
# ---------------------------------------------------------------------------

def calibrate_activation_scales(graph, params, rows) -> Dict[str, float]:
    """Per-dense-vertex activation scales off one forward pass of the
    probe batch: for each dense vertex, the amax of its INPUT activation
    (the producing vertex's output, through the consumer's preprocessor
    when one exists — a reshape preserves amax, but exactness is free
    here) mapped onto ±127. Deterministic: same rows, same params ⇒
    bit-identical scales."""
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.nn.layers import DenseLayer

    rows = jnp.asarray(np.asarray(rows, dtype=np.float32))
    acts = graph.feed_forward(params, rows, train=False)
    scales: Dict[str, float] = {}
    for v in graph.vertices:
        if v.layer is None or not isinstance(v.layer, DenseLayer):
            continue
        x = acts[v.inputs[0]]
        if v.preprocessor is not None:
            x = v.preprocessor(x)
        amax = float(jnp.max(jnp.abs(x)))
        scales[v.name] = max(amax, _AMAX_FLOOR) / _QMAX
    return scales


def quantize_dense_params(w, b, *, act_scale: float) -> Dict:
    """Per-output-channel symmetric weight quantization: scale_j maps the
    column's amax onto ±127, weights round-to-nearest into int8. Returns
    the QuantDenseLayer param dict (b passes through as float)."""
    import jax.numpy as jnp

    w = jnp.asarray(w, dtype=jnp.float32)
    amax = jnp.maximum(jnp.max(jnp.abs(w), axis=0), _AMAX_FLOOR)
    w_scale = (amax / _QMAX).astype(jnp.float32)
    w_q = jnp.clip(jnp.round(w / w_scale), -_QMAX, _QMAX).astype(jnp.int8)
    return {"W_q": w_q, "w_scale": w_scale,
            "b": jnp.asarray(b, dtype=jnp.float32)}


def quantize_classifier(graph, params, rows):
    """Graph surgery: every DenseLayer/OutputLayer vertex becomes a
    QuantDenseLayer carrying its calibrated activation scale; every other
    vertex (batchnorm, conv, activation) keeps its float form — standard
    PTQ practice, and the measured cost block prices the result honestly
    either way. Returns (quantized graph, quantized params, scales)."""
    from gan_deeplearning4j_tpu.nn.graph import ComputationGraph
    from gan_deeplearning4j_tpu.nn.layers import DenseLayer

    # import registers QuantDenseLayer for the from_dict rebuild below
    from gan_deeplearning4j_tpu.quant.layers import QuantDenseLayer  # noqa: F401

    scales = calibrate_activation_scales(graph, params, rows)
    spec = graph.to_dict()
    for node in spec["nodes"]:
        name = node["name"]
        if name not in scales:
            continue
        layer_d = node["layer"]
        node["layer"] = {
            "type": "QuantDenseLayer",
            "activation": layer_d.get("activation"),
            "weight_init": layer_d.get("weight_init"),
            "updater": layer_d.get("updater"),
            "l2": layer_d.get("l2"),
            "n_out": layer_d["n_out"],
            "n_in": layer_d.get("n_in"),
            "act_scale": scales[name],
        }
    qgraph = ComputationGraph.from_dict(spec)
    qparams = dict(params)
    for v in graph.vertices:
        if v.name in scales and isinstance(v.layer, DenseLayer):
            p = params[v.name]
            qparams[v.name] = quantize_dense_params(
                p["W"], p["b"], act_scale=scales[v.name])
    return qgraph, qparams, scales


# ---------------------------------------------------------------------------
# bf16 cast
# ---------------------------------------------------------------------------

def cast_params_bf16(params):
    """Float leaves → bfloat16 (the serializer stores them tagged-uint16);
    integer leaves (none today in serving checkpoints) pass through."""
    import jax
    import jax.numpy as jnp

    def _cast(leaf):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            return arr.astype(jnp.bfloat16)
        return arr

    return jax.tree_util.tree_map(_cast, params)


# ---------------------------------------------------------------------------
# bundle builders
# ---------------------------------------------------------------------------

def _base_quant_block(manifest: dict, source_dir: str, method: str) -> dict:
    return {
        "method": method,
        "source": os.path.basename(os.path.abspath(source_dir)),
        "source_generation": manifest.get("generation"),
        "source_step": manifest.get("step"),
        "built_unix": time.time(),
    }


def build_bf16_variant(source_dir: str, out_dir: str) -> dict:
    """Source bundle → bf16 bundle: every checkpoint's params cast to
    bfloat16, manifest gains ``precision: "bf16"`` + provenance. The
    serving engine maps the precision to a bfloat16 compute scope at AOT
    trace time (serving/engine.py), so storage AND matmul precision drop
    together — resident bytes halve, and the MXU runs its native path.
    Returns the written manifest."""
    from gan_deeplearning4j_tpu.utils.serializer import read_model, write_model

    manifest = read_bundle_manifest(source_dir)
    os.makedirs(out_dir, exist_ok=True)
    for key in ("generator", "classifier"):
        name = manifest.get(key)
        if not name:
            continue
        graph, params, _, _ = read_model(
            os.path.join(source_dir, name), load_updater=False)
        write_model(os.path.join(out_dir, name), graph,
                    cast_params_bf16(params), save_updater=False)
    manifest["precision"] = "bf16"
    manifest["quant"] = _base_quant_block(manifest, source_dir, "bf16_cast")
    write_bundle_manifest(out_dir, manifest)
    return manifest


def build_int8_variant(source_dir: str, out_dir: str, *,
                       calibration_rows: Optional[np.ndarray] = None,
                       calibration_seed: int = CALIBRATION_SEED) -> dict:
    """Source bundle → int8 bundle: the classifier's dense vertices are
    post-training-quantized against ``calibration_rows`` (the canary's
    probe batch when the caller has it; the seeded fallback stream
    otherwise), the generator checkpoint is copied byte-identical, and
    the manifest gains ``precision: "int8"`` + full calibration
    provenance (seed, row count, per-vertex scales). Returns the written
    manifest."""
    from gan_deeplearning4j_tpu.utils.serializer import read_model, write_model

    manifest = read_bundle_manifest(source_dir)
    cv_name = manifest.get("classifier")
    if not cv_name:
        raise ValueError(
            f"bundle at {source_dir} serves no classifier — int8 PTQ "
            f"quantizes the discriminator-feature classifier")
    os.makedirs(out_dir, exist_ok=True)

    graph, params, _, _ = read_model(
        os.path.join(source_dir, cv_name), load_updater=False)
    caller_rows = calibration_rows is not None
    if calibration_rows is None:
        calibration_rows = default_calibration_rows(
            graph.input_types[0].features, seed=calibration_seed)
    rows = np.asarray(calibration_rows, dtype=np.float32)
    qgraph, qparams, scales = quantize_classifier(graph, params, rows)
    write_model(os.path.join(out_dir, cv_name), qgraph, qparams,
                save_updater=False)

    gen_name = manifest.get("generator")
    if gen_name:
        shutil.copyfile(os.path.join(source_dir, gen_name),
                        os.path.join(out_dir, gen_name))

    manifest["precision"] = "int8"
    quant = _base_quant_block(manifest, source_dir,
                              "ptq_per_channel_symmetric")
    quant["calibration"] = {
        "seed": int(calibration_seed),
        "num_rows": int(rows.shape[0]),
        "source": "caller_probe_batch" if caller_rows else "seeded_fallback",
        "activation_scales": {k: float(v) for k, v in sorted(scales.items())},
    }
    manifest["quant"] = quant
    write_bundle_manifest(out_dir, manifest)
    return manifest
