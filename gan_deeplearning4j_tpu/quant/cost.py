"""Measured variant cost — the number the mux economics run on.

The mux plane's residency eviction and brownout shed ordering rank
variants by ``cost``. Before this module that number was operator-declared
fiction ("the bf16 sibling is cheaper, call it 1.0 vs 4.0") while every
variant secretly rode the same fp32 kernels. Here cost is a MEASUREMENT
taken on the live device ladder of a built engine:

- **per-bucket request latency** — ``engine.run`` timed per (kind,
  bucket) over the compiled ladder, min-of-rounds (the classic
  noise-floor estimator: minimum wall time is the run least disturbed by
  the host);
- **resident param bytes** — the device bytes one replica of the
  variant's parameters pins (bf16 halves them, int8 weights quarter
  them — the honest residency denominator);
- **staged width** — the pinned host staging bytes the variant's widest
  flush occupies per kind.

The scalar the registry ranks by is a *residency rent*:
``resident GiB × serve-seconds per kilorow`` — the memory×time a
kilorow of traffic holds on the device (the GB-seconds unit serverless
billing uses). It is measured, comparable across precisions, and robust
on tiny drill models where raw latency alone is dispatch-noise: the
bytes factor is exact while the latency factor is ±noise.

``write_cost_block`` lands the measurement in the variant's
``serving.json`` (atomic rewrite), so a bundle carries its own measured
economics: ``MuxRegistry.add(bundle_path=...)`` adopts the block and the
variant's ``cost_source`` flips from ``declared`` to ``measured``
(docs/MULTIPLEX.md, docs/QUANT.md).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import numpy as np

from gan_deeplearning4j_tpu.quant.variants import (
    read_bundle_manifest,
    write_bundle_manifest,
)

#: cost block schema version (manifest ``cost.cost_schema``)
COST_SCHEMA = 1

#: timing rounds per (kind, bucket) — min-of-rounds noise floor
DEFAULT_ROUNDS = 5


def _scalar(resident_bytes: int, per_row_s: float) -> float:
    """GiB·seconds of device residency per kilorow served."""
    return (resident_bytes / 2**30) * per_row_s * 1000.0


def measure_engine_cost(engine, *, rounds: int = DEFAULT_ROUNDS,
                        kinds: Optional[Sequence[str]] = None) -> dict:
    """Profile a built engine on its own compiled ladder. Warms the
    ladder first when needed (measuring a cold engine would time XLA
    compiles, not serving). Returns the manifest ``cost`` block."""
    if not engine.warmed:
        engine.warmup()
    kinds = list(kinds or engine.kinds)
    if not kinds:
        raise ValueError("engine serves no request kinds to measure")
    per_bucket: Dict[str, Dict[str, float]] = {}
    staged_widths: Dict[str, int] = {}
    for kind in kinds:
        width = engine.input_width(kind)
        staged_widths[kind] = width
        timings: Dict[str, float] = {}
        for bucket in engine.buckets:
            rows = np.zeros((bucket, width), np.float32)
            best = float("inf")
            for _ in range(max(1, rounds)):
                t0 = time.perf_counter()
                engine.run(kind, rows)
                best = min(best, time.perf_counter() - t0)
            timings[str(bucket)] = best
        per_bucket[kind] = timings
    top = max(engine.buckets)
    per_row_s = (sum(per_bucket[k][str(top)] for k in kinds)
                 / len(kinds)) / top
    resident = engine.resident_param_bytes()
    return {
        "cost_schema": COST_SCHEMA,
        "scalar": _scalar(resident, per_row_s),
        "scalar_unit": "GiB*s_per_kilorow",
        "per_row_s": per_row_s,
        "per_bucket_s": per_bucket,
        "resident_param_bytes": resident,
        "staged_widths": staged_widths,
        "staged_bytes_top_bucket": {
            k: top * w * 4 for k, w in staged_widths.items()},
        "buckets": list(engine.buckets),
        "replicas": engine.replica_count,
        "precision": getattr(engine, "precision", None) or "fp32",
        "platform": engine.platform,
        "rounds": int(rounds),
        "measured_unix": time.time(),
    }


def write_cost_block(bundle_dir: str, block: dict) -> dict:
    """Fold a measured cost block into the bundle's ``serving.json``
    (atomic rewrite — a concurrent from_bundle load never sees a torn
    manifest). Returns the updated manifest."""
    manifest = read_bundle_manifest(bundle_dir)
    manifest["cost"] = block
    write_bundle_manifest(bundle_dir, manifest)
    return manifest


def measure_bundle_cost(bundle_dir: str, *, buckets=None, replicas: int = 1,
                        rounds: int = DEFAULT_ROUNDS,
                        write: bool = True) -> dict:
    """Build the bundle's engine off to the side (no generation gauge
    claim), measure it, and (by default) write the ``cost`` block back
    into its manifest — the one-call path benches and drills use.
    ``buckets=None`` resolves the bundle's own learned ladder when the
    manifest carries one (serving/ladder.py) — a variant with
    traffic-shaped buckets is priced on the ladder it actually serves."""
    from gan_deeplearning4j_tpu.serving.engine import ServingEngine

    engine = ServingEngine.from_bundle(
        bundle_dir, buckets=buckets,
        replicas=replicas, export_gauge=False)
    block = measure_engine_cost(engine, rounds=rounds)
    if write:
        write_cost_block(bundle_dir, block)
    return block


def manifest_cost(bundle_dir: str) -> Optional[dict]:
    """The bundle's measured cost block, or None when the manifest has
    none (or cannot be read — a missing measurement is a bootstrap case,
    never an error)."""
    try:
        manifest = read_bundle_manifest(bundle_dir)
    except (OSError, ValueError):
        return None
    block = manifest.get("cost")
    if (isinstance(block, dict)
            and isinstance(block.get("scalar"), (int, float))
            and block["scalar"] > 0):
        return block
    return None


__all__ = [
    "COST_SCHEMA",
    "measure_engine_cost",
    "measure_bundle_cost",
    "write_cost_block",
    "manifest_cost",
]
