"""Updater specs + update rules (DL4J parity).

``RmsProp(lr, rmsDecay, epsilon)`` matches DL4J's RmsPropUpdater:

    cache ← decay * cache + (1 - decay) * g²      (cache initialized to eps)
    Δ     = lr * g / sqrt(cache + eps)

The reference instantiates it with decay = eps = 1e-8
(dl4jGANComputerVision.java:133,187,242 et al.), making cache ≈ g² and the
update ≈ lr·sign(g) — SURVEY §7 calls out that this near-sign-SGD behavior must
be reproduced faithfully, not replaced by a library default (optax's rmsprop
keeps a long-decay moving average; at decay 1e-8 the DL4J rule is a different
optimizer in practice).

Learning rate 0.0 is the freezing mechanism (:84): the update is exactly zero
but state still advances, matching DL4J (frozen layers' updater state is still
serialized and copied around).

Updaters are *specs* (hashable config); state creation and application are pure
functions so the whole optimizer step jits.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class UpdaterSpec:
    learning_rate: float = 0.0

    @property
    def kind(self) -> str:
        return type(self).__name__.lower()

    def init_state(self, param) -> Dict[str, Any]:
        return {}

    def apply(self, state: Dict[str, Any], grad, param) -> Tuple[Any, Dict[str, Any]]:
        """Returns (delta_to_subtract, new_state). Every in-tree rule is
        elementwise with scalar hyperparameters, so ``apply`` works
        unchanged on any shape — including the packed 1-D/row slices the
        update-sharding plan feeds it."""
        raise NotImplementedError

    def init_state_packed(self, packed_param) -> Dict[str, Any]:
        """State for a packed shard slice of trainable elements (the
        update-sharding layout): the elementwise image of
        :meth:`init_state`, with scalar slots broadcast per element
        (Adam's ``t``) so the whole update stays elementwise. Values are
        bit-identical to packing the tree-form init."""
        out: Dict[str, Any] = {}
        for field, value in self.init_state(packed_param).items():
            value = jnp.asarray(value)
            if value.ndim == 0:
                value = jnp.broadcast_to(value, jnp.shape(packed_param))
            out[field] = value
        return out

    def with_learning_rate(self, lr: float) -> "UpdaterSpec":
        return dataclasses.replace(self, learning_rate=lr)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["type"] = self.kind
        return d


@dataclasses.dataclass(frozen=True)
class Sgd(UpdaterSpec):
    learning_rate: float = 0.01

    def apply(self, state, grad, param):
        del param
        return self.learning_rate * grad, state


@dataclasses.dataclass(frozen=True)
class NoOp(UpdaterSpec):
    """Never updates (hard-freeze alternative to lr=0)."""

    def apply(self, state, grad, param):
        return jnp.zeros_like(param), state


@dataclasses.dataclass(frozen=True)
class RmsProp(UpdaterSpec):
    """DL4J RmsPropUpdater. Reference config: RmsProp(lr, 1e-8, 1e-8)."""

    learning_rate: float = 0.001
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init_state(self, param):
        # DL4J initializes the cache to epsilon (avoids div-by-zero on step 1)
        return {"cache": jnp.full_like(param, self.epsilon)}

    def apply(self, state, grad, param):
        del param
        cache = state["cache"] * self.rms_decay + (grad**2) * (1.0 - self.rms_decay)
        delta = grad * self.learning_rate / jnp.sqrt(cache + self.epsilon)
        return delta, {"cache": cache}


@dataclasses.dataclass(frozen=True)
class Adam(UpdaterSpec):
    """Adam (named in the BASELINE.json north star; unused by the reference's
    own graphs, which are RmsProp-only — provided for the wider configs)."""

    learning_rate: float = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, param):
        return {
            "m": jnp.zeros_like(param),
            "v": jnp.zeros_like(param),
            "t": jnp.zeros((), jnp.int32),
        }

    def apply(self, state, grad, param):
        del param
        t = state["t"] + 1
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * grad**2
        tf = t.astype(jnp.float32)
        m_hat = m / (1 - self.beta1**tf)
        v_hat = v / (1 - self.beta2**tf)
        delta = self.learning_rate * m_hat / (jnp.sqrt(v_hat) + self.epsilon)
        return delta, {"m": m, "v": v, "t": t}


def updater_from_dict(d: dict) -> UpdaterSpec:
    d = dict(d)
    kind = d.pop("type")
    classes = {"sgd": Sgd, "noop": NoOp, "rmsprop": RmsProp, "adam": Adam}
    if kind not in classes:
        raise KeyError(f"unknown updater type {kind!r}")
    return classes[kind](**d)
