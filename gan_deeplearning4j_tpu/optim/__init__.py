"""Optimizers — per-layer updaters with DL4J-parity semantics (SURVEY §2.2 D8-D9).

The reference attaches ``new RmsProp(lr, 1e-8, 1e-8)`` to every layer
individually, uses learning-rate 0.0 as the freezing mechanism
(dl4jGANComputerVision.java:84,187,277), clips gradients elementwise at 1.0 and
applies L2 1e-4 — all reproduced here, with updater state shaped like the param
tree so it checkpoints alongside params (ModelSerializer saveUpdater analog,
:605-619).
"""

from gan_deeplearning4j_tpu.optim.updaters import Adam, NoOp, RmsProp, Sgd, UpdaterSpec, updater_from_dict
from gan_deeplearning4j_tpu.optim.optimizer import GraphOptimizer

__all__ = [
    "UpdaterSpec",
    "RmsProp",
    "Sgd",
    "Adam",
    "NoOp",
    "updater_from_dict",
    "GraphOptimizer",
]
