"""GraphOptimizer — per-layer updater application (SURVEY §2.2 D8-D9).

Binds a ComputationGraph's per-layer updater specs (the reference's
``.updater(new RmsProp(lr,1e-8,1e-8))`` on every layer) into one jittable
update step:

1. gradient normalization per the graph config (the reference clips
   elementwise at 1.0, dl4jGANComputerVision.java:124-125);
2. each layer's updater applied per parameter, LR 0.0 giving exact freezing;
3. BatchNorm running stats (role "state") are never touched by the optimizer —
   they update through the training forward pass.

The optimizer state tree mirrors the trainable param tree, so it serializes
alongside params (the ``saveUpdater=true`` analog, :605-619) and shards the
same way under pjit.

L2 note: the reference's L2 1e-4 enters through the loss
(``ComputationGraph.l2_penalty``), so ``jax.grad`` already contains the
``l2 * W`` term — matching DL4J, which adds the regularization gradient
before the updater sees it.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.ops import clipping


class GraphOptimizer:
    """Per-layer optimizer for a ComputationGraph's parameters."""

    def __init__(self, graph):
        self._updaters = graph.layer_updaters()
        self._roles = graph.param_roles()
        self._clip = graph.config.gradient_clip
        self._clip_value = graph.config.gradient_clip_value

    @property
    def updaters(self) -> Dict:
        """Per-layer updater specs — the update-sharding plan's group key."""
        return self._updaters

    def trainable(self, layer: str, pname: str) -> bool:
        return (
            layer in self._updaters
            and self._roles.get(layer, {}).get(pname) != "state"
        )

    def init(self, params: Dict, keys: Optional[Iterable[Tuple[str, str]]] = None) -> Dict:
        """Updater state tree: {layer: {param: state_dict}} for trainable
        params. ``keys`` restricts init to a shard slice of (layer, pname)
        pairs — the tree-granularity half of the shard-slice init surface
        (the packed half is ``UpdaterSpec.init_state_packed``, which the
        update-sharding plan consumes). Nothing in the restore paths needs
        it today: elastic restores re-init missing updaters WHOLE and
        re-pack, so this exists for callers that want a per-shard tree
        without materializing the rest."""
        wanted = None if keys is None else set(keys)
        state: Dict = {}
        for layer, updater in self._updaters.items():
            state[layer] = {
                pname: updater.init_state(p)
                for pname, p in params[layer].items()
                if self.trainable(layer, pname)
                and (wanted is None or (layer, pname) in wanted)
            }
        return state

    def state_structs(self, params: Dict) -> Dict:
        """The updater state tree as ShapeDtypeStructs (no buffers) —
        what the update-sharding plan derives its packed layout and flat
        key namespace from."""
        return jax.eval_shape(self.init, params)

    def clip_grads(self, grads: Dict) -> Dict:
        """The graph-config gradient normalization (step 1 of :meth:`step`),
        shared verbatim by the sharded update path — clipping happens on
        the replicated gradients in both modes, so the per-element update
        inputs are identical."""
        if self._clip == "elementwise":
            return clipping.clip_elementwise(grads, self._clip_value)
        if self._clip == "global_norm":
            return clipping.clip_by_global_norm(grads, self._clip_value)
        if self._clip is not None:
            raise ValueError(f"unknown gradient_clip {self._clip!r}")
        return grads

    def step(self, params: Dict, grads: Dict, opt_state: Dict,
             lr_scale=None) -> Tuple[Dict, Dict]:
        """One update: returns (new_params, new_opt_state). Pure — safe under
        jit; donate the inputs for in-place HBM reuse.

        ``lr_scale`` (a traced scalar or None) multiplies the final delta.
        Every in-tree updater's delta is LINEAR in its learning rate (SGD,
        DL4J-RmsProp, Adam — optim/updaters.py), so scaling the delta is
        exactly an effective-LR rescale — the mechanism behind the dis-LR
        decay schedule (ExperimentConfig.dis_lr_decay_*) without baking the
        rate into the compiled program."""
        grads = self.clip_grads(grads)

        new_params = dict(params)
        new_state = dict(opt_state)
        for layer, updater in self._updaters.items():
            layer_params = dict(new_params[layer])
            layer_state = dict(new_state.get(layer, {}))
            for pname, p in layer_params.items():
                if not self.trainable(layer, pname):
                    continue
                delta, s = updater.apply(layer_state[pname], grads[layer][pname], p)
                if lr_scale is not None:
                    # cast to the delta's dtype: an f32 scale on a bf16 delta
                    # would silently promote params out of bf16 storage
                    delta = delta * jnp.asarray(lr_scale, delta.dtype)
                layer_params[pname] = p - delta
                layer_state[pname] = s
            new_params[layer] = layer_params
            new_state[layer] = layer_state
        return new_params, new_state
