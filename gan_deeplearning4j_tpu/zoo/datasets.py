"""Zoo dataset loaders — one seeded, offline-deterministic source per
scenario dataset axis (docs/ZOO.md).

Every loader honors the contract of ``data/mnist.synthetic_mnist``:
``((x_train, y_train), (x_test, y_test))`` with ``x`` float32 in [0,1] of
shape ``(N, num_features)`` row-major (h, w, c flattened) and ``y`` int64
class labels. This image has no network egress, so — exactly like the MNIST
plane — each dataset is a deterministic class-template synthesis: smooth
per-class fields with seeded jitter, distinct enough that the transfer
classifier has real signal. ``fashion_mnist`` and ``cifar_shaped`` use
DIFFERENT template seeds and textures from MNIST so a canary gate comparing
across datasets sees genuinely mismatched statistics (deploy/canary.py fails
closed before that comparison can happen).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np

from gan_deeplearning4j_tpu.data.mnist import synthetic_mnist
from gan_deeplearning4j_tpu.zoo.manifest import DATASET_SHAPES

Split = Tuple[np.ndarray, np.ndarray]
LoadResult = Tuple[Split, Split]

NUM_CLASSES = 10

# Template seeds are per-dataset constants, NOT derived from the caller's
# seed: two runs of different datasets at the same seed must still draw from
# different distributions, or the canary's dataset-identity gate would be
# untestable.
_TEMPLATE_SEED = {"fashion_mnist": 13_666, "cifar_shaped": 32_666}


def _smooth_field(rng: np.random.Generator, side: int, waves: int) -> np.ndarray:
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32) / side
    field = np.zeros((side, side), dtype=np.float32)
    for _ in range(waves):
        fx, fy = rng.uniform(0.5, 4.0, size=2)
        px, py = rng.uniform(0, 2 * np.pi, size=2)
        field += rng.uniform(0.3, 1.0) * np.cos(2 * np.pi * fx * xx + px) * np.cos(
            2 * np.pi * fy * yy + py
        )
    return (field - field.min()) / (field.max() - field.min() + 1e-8)


def _garment_templates(side: int, seed: int) -> np.ndarray:
    """Fashion-MNIST-like glyphs: blocky garment silhouettes (rectangular
    masks with seeded cut-outs) filled with smooth texture — distinct from
    MNIST's vignetted stroke fields in both silhouette and spectrum."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32) / side
    templates = np.zeros((NUM_CLASSES, side, side), dtype=np.float32)
    for c in range(NUM_CLASSES):
        top, bottom = rng.uniform(0.05, 0.25), rng.uniform(0.75, 0.95)
        left, right = rng.uniform(0.1, 0.3), rng.uniform(0.7, 0.9)
        mask = ((yy >= top) & (yy <= bottom) & (xx >= left) & (xx <= right))
        if rng.uniform() < 0.5:  # sleeves / straps: side lobes
            mask |= (yy >= top) & (yy <= top + 0.2) & ((xx < left) | (xx > right))
        templates[c] = mask.astype(np.float32) * (
            0.35 + 0.65 * _smooth_field(rng, side, waves=4)
        )
    return templates


def _scene_templates(side: int, channels: int, seed: int) -> np.ndarray:
    """CIFAR-shaped scenes: per-channel smooth fields plus a class-specific
    centered blob, giving each class a distinct dominant hue and layout."""
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32) / side
    templates = np.zeros((NUM_CLASSES, side, side, channels), dtype=np.float32)
    for c in range(NUM_CLASSES):
        cx, cy = rng.uniform(0.3, 0.7, size=2)
        blob = np.exp(-((xx - cx) ** 2 + (yy - cy) ** 2) / rng.uniform(0.02, 0.08))
        hue = rng.dirichlet(np.ones(channels)).astype(np.float32)
        for ch in range(channels):
            templates[c, :, :, ch] = np.clip(
                0.5 * _smooth_field(rng, side, waves=5) + hue[ch] * blob, 0.0, 1.0
            )
    return templates


def _synthesize(
    templates: np.ndarray,
    num_train: int,
    num_test: int,
    seed: int,
    noise: float,
    max_shift: int,
) -> LoadResult:
    side = templates.shape[1]
    feat = int(np.prod(templates.shape[1:]))
    rng = np.random.default_rng(seed + 1)

    def make(n: int) -> Split:
        labels = rng.integers(0, NUM_CLASSES, size=n)
        imgs = templates[labels].copy()
        shifts = rng.integers(-max_shift, max_shift + 1, size=(n, 2))
        for i in range(n):
            imgs[i] = np.roll(imgs[i], shifts[i], axis=(0, 1))
        imgs += rng.normal(0.0, noise, size=imgs.shape).astype(np.float32)
        imgs = np.clip(imgs, 0.0, 1.0)
        return imgs.reshape(n, feat).astype(np.float32), labels.astype(np.int64)

    del side  # shape bookkeeping only
    return make(num_train), make(num_test)


def load_fashion_mnist(
    num_train: int = 2000, num_test: int = 500, seed: int = 666
) -> LoadResult:
    side = DATASET_SHAPES["fashion_mnist"][0]
    templates = _garment_templates(side, _TEMPLATE_SEED["fashion_mnist"])
    return _synthesize(templates, num_train, num_test, seed, noise=0.06, max_shift=1)


def load_cifar_shaped(
    num_train: int = 2000, num_test: int = 500, seed: int = 666
) -> LoadResult:
    h, w, c = DATASET_SHAPES["cifar_shaped"]
    templates = _scene_templates(h, c, _TEMPLATE_SEED["cifar_shaped"])
    return _synthesize(templates, num_train, num_test, seed, noise=0.05, max_shift=2)


def load_mnist(
    num_train: int = 2000, num_test: int = 500, seed: int = 666
) -> LoadResult:
    return synthetic_mnist(num_train=num_train, num_test=num_test, seed=seed)


LOADERS: Dict[str, Callable[..., LoadResult]] = {
    "mnist": load_mnist,
    "fashion_mnist": load_fashion_mnist,
    "cifar_shaped": load_cifar_shaped,
}


def load_dataset(
    name: str, num_train: int = 2000, num_test: int = 500, seed: int = 666
) -> LoadResult:
    """Load a zoo dataset by its manifest name. Raises on unknown names —
    the manifest validated the axis, so an unknown name here is a bug."""
    try:
        loader = LOADERS[name]
    except KeyError:
        raise ValueError(
            f"unknown zoo dataset {name!r} (want one of {sorted(LOADERS)})"
        ) from None
    return loader(num_train=num_train, num_test=num_test, seed=seed)
