"""Model zoo — manifest-driven scenarios beyond unconditional MNIST-DCGAN.

See docs/ZOO.md. The manifest (:mod:`zoo.manifest`) is the single scenario
descriptor the harness, serializer, serving engine, canary gate, and mux
drills all key off; :mod:`zoo.datasets` holds the per-dataset loaders and
:mod:`zoo.streaming` the double-buffered input pipeline.
"""

from gan_deeplearning4j_tpu.zoo.manifest import (
    ARCHITECTURES,
    CONDITIONINGS,
    DATASET_SHAPES,
    DATASETS,
    ScenarioManifest,
    scenario_from_bundle,
    scenario_from_config,
)

__all__ = [
    "ARCHITECTURES",
    "CONDITIONINGS",
    "DATASETS",
    "DATASET_SHAPES",
    "ScenarioManifest",
    "scenario_from_bundle",
    "scenario_from_config",
]
