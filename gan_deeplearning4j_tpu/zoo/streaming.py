"""Streaming input pipeline — host-side double buffering behind the
DataSetIterator contract (docs/ZOO.md, the TensorFlow-system input-pipeline
pattern from PAPERS.md).

``ArrayDataSetIterator`` holds the whole dataset as one resident float32
matrix; fine for MNIST, wrong as the zoo adds datasets whose size should not
be coupled to the step loop. ``StreamingDataSetIterator`` keeps only two
BLOCKS resident (a block is ``block_batches`` batches): the consumer slices
batches out of the current block while a single background worker
materializes the next block from the row ``source``. The promotion FENCES on
the worker's future before the consumer ever reads the incoming buffer — the
exact discipline jaxlint JG032 (double-buffer-misuse) enforces statically.

Bit-exactness is the contract, not an aspiration: epoch order is the same
seeded permutation (``default_rng(seed + epoch)``), rows are cast to float32
the same way, and batches are the same ``order[cursor:cursor+batch_size]``
slices — so at matched seed the streamed batches are byte-identical to the
in-memory iterator's (tests/test_zoo.py proves it). Training through it is
therefore a data-plane swap with zero step-loop changes.
"""

from __future__ import annotations

from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from gan_deeplearning4j_tpu.data.dataset import DataSet
from gan_deeplearning4j_tpu.data.iterator import DataSetIterator

# source(indices) -> (feature_rows, label_rows | None) for the given global
# row indices, in order. Called from the prefetch worker thread — must be
# thread-compatible (pure reads).
RowSource = Callable[[np.ndarray], Tuple[np.ndarray, Optional[np.ndarray]]]


def array_source(
    features: np.ndarray, labels: Optional[np.ndarray] = None
) -> Tuple[RowSource, int]:
    """Adapt in-memory arrays to the row-source contract. The float32 cast
    happens HERE, once — mirroring ArrayDataSetIterator's constructor cast —
    so streamed rows are bit-identical to the in-memory iterator's."""
    feats = np.asarray(features, dtype=np.float32)
    labs = None if labels is None else np.asarray(labels, dtype=np.float32)
    if labs is not None and labs.shape[0] != feats.shape[0]:
        raise ValueError("features/labels row mismatch")

    def source(idx: np.ndarray):
        return feats[idx], (None if labs is None else labs[idx])

    return source, feats.shape[0]


def npz_source(
    path: str, features_key: str = "features", labels_key: str = "labels"
) -> Tuple[RowSource, int]:
    """Row source over an ``.npz`` file (the drills' workload format). The
    file is opened once; row gathers run in the prefetch worker, so the
    consumer thread never touches the file."""
    archive = np.load(path)
    feats = np.asarray(archive[features_key], dtype=np.float32)
    labs = (
        np.asarray(archive[labels_key], dtype=np.float32)
        if labels_key in archive.files
        else None
    )
    return array_source(feats, labs)


class StreamingDataSetIterator(DataSetIterator):
    """Double-buffered DataSetIterator over a row source.

    Two buffers: the CURRENT block (being consumed batch-by-batch) and the
    PENDING block (being filled by the worker). ``_promote`` is the only
    place the pending buffer becomes readable, and it calls
    ``Future.result()`` first — the fence. (It is a promotion, not a
    concurrent swap seam: the consumer is single-threaded and the worker
    never touches ``_block``, so no lock is needed — which is also why the
    method is not named ``swap``; JG016's lock discipline is for engines
    hot-swapped under other threads.) Blocks are batch-aligned (``block_batches *
    batch_size`` rows), so no batch ever straddles a buffer boundary; the
    ragged tail (``drop_remainder=False``) is simply the last block's short
    final slice, same as the in-memory iterator.
    """

    def __init__(
        self,
        source: RowSource,
        num_rows: int,
        batch_size: int = 128,
        shuffle: bool = False,
        seed: int = 666,
        drop_remainder: bool = False,
        block_batches: int = 8,
    ):
        if block_batches < 1:
            raise ValueError("block_batches must be >= 1")
        self._source = source
        self.num_rows = int(num_rows)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self._block_rows = block_batches * self.batch_size
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="zoo-stream"
        )
        self._epoch = 0
        self._start_epoch()

    # -- epoch / block machinery ---------------------------------------------
    def _make_order(self) -> np.ndarray:
        # Identical to ArrayDataSetIterator._make_order: that identity IS the
        # bit-exactness guarantee.
        if not self.shuffle:
            return np.arange(self.num_rows)
        rng = np.random.default_rng(self.seed + self._epoch)
        return rng.permutation(self.num_rows)

    def _start_epoch(self) -> None:
        self._order = self._make_order()
        self._cursor = 0
        self._block: Optional[Tuple[int, np.ndarray, Optional[np.ndarray]]] = None
        self._pending: Optional[Tuple[int, Future]] = None
        self._issue(0)
        self._promote()

    def _materialize(self, idx: np.ndarray):
        feats, labs = self._source(idx)
        feats = np.asarray(feats, dtype=np.float32)
        labs = None if labs is None else np.asarray(labs, dtype=np.float32)
        return feats, labs

    def _issue(self, start: int) -> None:
        """Kick off the overlapped fill of the block starting at ``start``."""
        if start >= len(self._order):
            self._pending = None
            return
        idx = self._order[start : start + self._block_rows]
        self._pending = (start, self._executor.submit(self._materialize, idx))

    def _promote(self) -> None:
        """Promote the pending buffer to current. The ``result()`` call is
        the FENCE: the consumer must never read a buffer whose fill is still
        in flight (jaxlint JG032)."""
        if self._pending is None:
            self._block = None
            return
        start, future = self._pending
        feats, labs = future.result()
        self._block = (start, feats, labs)
        self._issue(start + len(feats))

    # -- DataSetIterator protocol --------------------------------------------
    def has_next(self) -> bool:
        remaining = self.num_rows - self._cursor
        if self.drop_remainder:
            return remaining >= self.batch_size
        return remaining > 0

    def next(self) -> DataSet:
        if not self.has_next() or self._block is None:
            raise StopIteration
        start, feats, labs = self._block
        offset = self._cursor - start
        rows = feats[offset : offset + self.batch_size]
        self._cursor += len(rows)
        batch = DataSet(
            jnp.asarray(rows),
            None if labs is None else jnp.asarray(labs[offset : offset + len(rows)]),
        )
        if self._cursor >= start + len(feats):
            self._promote()
        return batch

    def reset(self) -> None:
        # Fence any in-flight fill before discarding its target buffer, then
        # rebuild the epoch order (epoch increments first, matching
        # ArrayDataSetIterator.reset's permutation schedule).
        if self._pending is not None:
            self._pending[1].result()
            self._pending = None
        self._epoch += 1
        self._start_epoch()

    def close(self) -> None:
        """Release the worker thread. Safe to call more than once; the
        iterator is unusable afterwards."""
        if self._pending is not None:
            self._pending[1].result()
            self._pending = None
        self._executor.shutdown(wait=True)
