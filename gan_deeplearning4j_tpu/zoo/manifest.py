"""ScenarioManifest — the model zoo's one typed scenario descriptor.

Everything downstream of training keys off this object instead of assuming
unconditional MNIST-DCGAN (ROADMAP "Scenario diversity"): the harness builds
its ``ExperimentConfig`` from it, the serializer embeds it in a bundle's
``serving.json`` under the ``"zoo"`` key, the serving engine reads it back to
decide whether ``POST /v1/sample?class=k`` is legal, and the canary gate
refuses to FID-score a candidate against reals of a different dataset.

The axes (docs/ZOO.md):

- ``architecture``: ``"dcgan"`` (the reference's alternating XENT loop,
  GraphTrainer families) or ``"wgan_gp"`` (critic-round program,
  models/wgan_gp.py).
- ``conditioning``: ``"none"`` or ``"class"`` — class-conditional widens the
  generator input to ``[z | one-hot(class)]`` (harness/experiment.py); the
  discriminator stays unconditional so the paper's transfer claim is
  untouched.
- ``dataset``: ``"mnist"`` | ``"fashion_mnist"`` | ``"cifar_shaped"`` — the
  identity of the real rows (zoo/datasets.py loaders). Resolution is
  dataset-native and validated, not free.

Validation encodes the real architectural constraints rather than wishful
ones: the image/WGAN-GP stem uses ``stages_for(height, width)`` which
requires power-of-two sides, so ``wgan_gp`` only builds at the 32×32
``cifar_shaped`` dataset; MNIST-shaped 28×28 datasets map to the proven
"mnist" DCGAN family. ``wgan_gp`` + ``conditioning='class'`` is rejected
(queued in ROADMAP.md) — config.py enforces the same pair server-side.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional

ARCHITECTURES = ("dcgan", "wgan_gp")
CONDITIONINGS = ("none", "class")
DATASETS = ("mnist", "fashion_mnist", "cifar_shaped")

# dataset -> (height, width, channels): the native shape of its real rows.
# Resolution is NOT a free axis — a scenario's ``resolution`` must equal the
# native side (square datasets only), which keeps "resolution" in the
# manifest as documentation of the serving surface rather than a second
# source of truth that could drift from the loader.
DATASET_SHAPES: Dict[str, tuple] = {
    "mnist": (28, 28, 1),
    "fashion_mnist": (28, 28, 1),
    "cifar_shaped": (32, 32, 3),
}


@dataclasses.dataclass(frozen=True)
class ScenarioManifest:
    architecture: str = "dcgan"
    conditioning: str = "none"
    dataset: str = "mnist"
    resolution: int = 28
    num_classes: int = 10
    z_size: int = 2

    def __post_init__(self) -> None:
        if self.architecture not in ARCHITECTURES:
            raise ValueError(
                f"unknown architecture {self.architecture!r} "
                f"(want one of {ARCHITECTURES})"
            )
        if self.conditioning not in CONDITIONINGS:
            raise ValueError(
                f"unknown conditioning {self.conditioning!r} "
                f"(want one of {CONDITIONINGS})"
            )
        if self.dataset not in DATASETS:
            raise ValueError(
                f"unknown dataset {self.dataset!r} (want one of {DATASETS})"
            )
        native = DATASET_SHAPES[self.dataset][0]
        if self.resolution != native:
            raise ValueError(
                f"dataset {self.dataset!r} is {native}x{native}; "
                f"resolution {self.resolution} is not an independent axis"
            )
        if self.architecture == "wgan_gp":
            if self.dataset != "cifar_shaped":
                # dcgan_image.stages_for requires power-of-two sides: the
                # 28x28 datasets cannot build the conv stem.
                raise ValueError(
                    "wgan_gp's conv stem (stages_for) needs power-of-two "
                    f"sides — dataset {self.dataset!r} is "
                    f"{native}x{native}; use dataset='cifar_shaped'"
                )
            if self.conditioning == "class":
                raise ValueError(
                    "wgan_gp + conditioning='class' is queued (ROADMAP.md); "
                    "the critic-round program is unconditional"
                )
        if self.conditioning == "class" and self.num_classes < 2:
            raise ValueError(
                "class-conditional scenarios need num_classes >= 2"
            )
        if self.z_size < 1:
            raise ValueError(f"z_size {self.z_size} must be >= 1")

    # -- derived identities --------------------------------------------------
    @property
    def shape(self) -> tuple:
        """(height, width, channels) of the dataset's rows."""
        return DATASET_SHAPES[self.dataset]

    @property
    def num_features(self) -> int:
        h, w, c = self.shape
        return h * w * c

    @property
    def family_name(self) -> str:
        """The models/registry.py family this scenario trains under."""
        if self.architecture == "wgan_gp":
            return "wgan_gp"
        # dcgan: the 28x28 datasets run the reference's fixed-28x28 MNIST
        # graph (7*7*128 stem); power-of-two sides run the shape-generic
        # image family.
        return "mnist" if self.shape[0] == 28 else "image"

    @property
    def conditional(self) -> bool:
        return self.conditioning == "class"

    @property
    def sample_input_width(self) -> int:
        """Serving-side ``sample`` kind input width: z, plus the one-hot
        label embedding for conditional scenarios."""
        return self.z_size + (self.num_classes if self.conditional else 0)

    # -- config / dict plumbing ----------------------------------------------
    def experiment_config(self, **overrides: Any):
        """Materialize an ``ExperimentConfig`` for this scenario.

        Lazy import: harness/config.py validates against the model registry,
        which must not import zoo/ at module scope (cycle)."""
        from gan_deeplearning4j_tpu.harness.config import ExperimentConfig

        h, w, c = self.shape
        base = dict(
            model_family=self.family_name,
            conditioning=self.conditioning,
            dataset=self.dataset,
            height=h,
            width=w,
            channels=c,
            num_features=h * w * c,
            num_classes=self.num_classes,
            z_size=self.z_size,
        )
        base.update(overrides)
        return ExperimentConfig(**base).validate()

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(doc: Dict[str, Any]) -> "ScenarioManifest":
        fields = {f.name for f in dataclasses.fields(ScenarioManifest)}
        unknown = set(doc) - fields
        if unknown:
            raise ValueError(f"unknown scenario manifest keys {sorted(unknown)}")
        return ScenarioManifest(**doc)


def scenario_from_config(cfg) -> Optional[ScenarioManifest]:
    """Recover the scenario a config trains, or None when the config falls
    outside the zoo's axes (tabular family, non-native shapes, legacy
    configs). None means 'publish an unconditional legacy bundle' — never an
    error: the zoo is additive over the existing single-scenario plane."""
    from gan_deeplearning4j_tpu.models import registry

    try:
        family = registry.get(cfg.model_family).name
    except Exception:
        return None
    if family == "wgan_gp":
        architecture = "wgan_gp"
    elif family in ("mnist", "image"):
        architecture = "dcgan"
    else:
        return None  # tabular and friends live outside the image zoo
    dataset = getattr(cfg, "dataset", "mnist")
    if (cfg.height, cfg.width, cfg.channels) != DATASET_SHAPES.get(dataset):
        # the config trains some other shape (tiny test configs, legacy
        # image runs) — an honest manifest must not claim a zoo dataset
        # whose native shape the model doesn't actually have
        return None
    try:
        return ScenarioManifest(
            architecture=architecture,
            conditioning=getattr(cfg, "conditioning", "none"),
            dataset=dataset,
            resolution=DATASET_SHAPES.get(dataset, (cfg.height,))[0],
            num_classes=cfg.num_classes,
            z_size=cfg.z_size,
        )
    except (ValueError, KeyError):
        return None


def scenario_from_bundle(directory: str) -> Optional[ScenarioManifest]:
    """Read the scenario block out of a serving bundle's manifest.

    Returns None for pre-zoo bundles (no ``"zoo"`` key) — those serve as
    before: unconditional, MNIST-assumed."""
    path = os.path.join(directory, "serving.json")
    with open(path) as fh:
        manifest = json.load(fh)
    doc = manifest.get("zoo")
    return None if doc is None else ScenarioManifest.from_dict(doc)
